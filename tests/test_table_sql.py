"""Table API + SQL slice (ref: flink-table's sqlQuery pipeline +
DataStreamGroupWindowAggregate lowering — SURVEY.md §2.5, BASELINE.md
config #5)."""

import collections

import numpy as np
import pytest

from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.sources import (
    BoundedOutOfOrdernessTimestampExtractor,
    CollectSink,
)
from flink_tpu.table import (
    SqlError,
    StreamTableEnvironment,
    Tumble,
    col,
)
from flink_tpu.table.sql_parser import parse


# ---------------------------------------------------------------------
# parser units
# ---------------------------------------------------------------------

def test_parse_select_where():
    q = parse("SELECT a, b + 1 AS c FROM t WHERE a > 2 AND b <> 0")
    assert q.table == "t"
    assert len(q.select) == 2
    assert q.where is not None
    assert q.window is None


def test_parse_tumble_group_by():
    q = parse("SELECT k, COUNT(*) FROM ev "
              "GROUP BY TUMBLE(ts, INTERVAL '1' SECOND), k")
    assert q.window.kind == "tumble"
    assert q.window.size_ms == 1000
    assert q.window.time_col == "ts"
    assert len(q.group_by) == 1


def test_parse_hop_and_session():
    q = parse("SELECT COUNT(*) FROM t GROUP BY "
              "HOP(ts, INTERVAL '1' SECOND, INTERVAL '10' SECOND)")
    assert q.window.kind == "hop"
    assert q.window.slide_ms == 1000 and q.window.size_ms == 10000
    q = parse("SELECT COUNT(*) FROM t GROUP BY "
              "SESSION(ts, INTERVAL '500' MILLISECOND)")
    assert q.window.kind == "session" and q.window.gap_ms == 500


def test_parse_errors():
    with pytest.raises(SqlError):
        parse("SELECT FROM t")
    with pytest.raises(SqlError):
        parse("SELECT a FROM t GROUP BY TUMBLE(ts, INTERVAL '1' FORTNIGHT)")


# ---------------------------------------------------------------------
# end-to-end SQL jobs
# ---------------------------------------------------------------------

def _sorted_events(n=600, n_keys=10, n_users=50, horizon=3000, seed=2):
    rng = np.random.default_rng(seed)
    return sorted(
        ((int(k), int(u), int(t)) for k, u, t in
         zip(rng.integers(0, n_keys, n), rng.integers(0, n_users, n),
             rng.integers(0, horizon, n))),
        key=lambda e: e[2])


def _table_env(events):
    env = StreamExecutionEnvironment()
    stream = env.from_collection(events)
    stream = stream.assign_timestamps_and_watermarks(
        BoundedOutOfOrdernessTimestampExtractor(0, lambda e: e[2]))
    t_env = StreamTableEnvironment.create(env)
    table = t_env.from_data_stream(stream, ["k", "u", "ts"], rowtime="ts")
    t_env.register_table("ev", table)
    return env, t_env


def test_sql_projection_and_filter():
    events = [(1, 10, 0), (2, 20, 10), (3, 30, 20)]
    env, t_env = _table_env(events)
    out = t_env.sql_query("SELECT k * 10, u FROM ev WHERE k <> 2")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("sql-proj")
    assert sorted(sink.values) == [(10, 10), (30, 30)]


def test_sql_tumble_count_sum(  ):
    events = _sorted_events()
    env, t_env = _table_env(events)
    out = t_env.sql_query(
        "SELECT k, COUNT(*) AS c, SUM(u) AS s, TUMBLE_START(ts) AS ws "
        "FROM ev GROUP BY TUMBLE(ts, INTERVAL '1' SECOND), k")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("sql-tumble")

    expect_c = collections.Counter()
    expect_s = collections.Counter()
    for k, u, t in events:
        w = t - t % 1000
        expect_c[(k, w)] += 1
        expect_s[(k, w)] += u
    got = {(k, ws): (c, s) for (k, c, s, ws) in sink.values}
    assert set(got) == set(expect_c)
    for key in expect_c:
        assert got[key] == (expect_c[key], expect_s[key])


def test_sql_approx_count_distinct_device_path():
    """Config #5: APPROX_COUNT_DISTINCT GROUP BY TUMBLE lowers onto the
    HLL device kernel (single-agg queries ride DeviceWindowOperator)."""
    events = _sorted_events(n=4000, n_keys=6, n_users=500)
    env, t_env = _table_env(events)
    out = t_env.sql_query(
        "SELECT k, APPROX_COUNT_DISTINCT(u) AS d "
        "FROM ev GROUP BY TUMBLE(ts, INTERVAL '1' SECOND), k")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("sql-acd")

    truth = collections.defaultdict(set)
    for k, u, t in events:
        truth[(k, t - t % 1000)].add(u)
    got = collections.defaultdict(list)
    for k, d in sink.values:
        got[k].append(d)
    assert sum(len(v) for v in got.values()) == len(truth)
    # HLL accuracy: within 15% at p12
    per_key_truth = collections.defaultdict(list)
    for (k, w), users in sorted(truth.items()):
        per_key_truth[k].append(len(users))
    for k, estimates in got.items():
        for est, exact in zip(sorted(estimates), sorted(per_key_truth[k])):
            assert abs(est - exact) <= max(2, 0.15 * exact)

    # the graph really built a DeviceWindowOperator
    from flink_tpu.streaming.device_window_operator import (
        DeviceWindowOperator,
    )
    nodes = env.graph.nodes.values()
    ops = [n.operator_factory() for n in nodes if "sql_window_agg" in n.name]
    assert ops and isinstance(ops[0], DeviceWindowOperator)


def test_sql_session_window_and_having():
    events = [(1, 5, 0), (1, 6, 100), (1, 7, 2000), (2, 8, 2100)]
    env, t_env = _table_env(events)
    out = t_env.sql_query(
        "SELECT k, COUNT(*) AS c FROM ev "
        "GROUP BY SESSION(ts, INTERVAL '500' MILLISECOND), k "
        "HAVING COUNT(*) > 1")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("sql-session")
    assert sink.values == [(1, 2)]


def test_sql_hop_window():
    events = [(1, 0, 500), (1, 0, 1500)]
    env, t_env = _table_env(events)
    out = t_env.sql_query(
        "SELECT k, COUNT(*) AS c, TUMBLE_START(ts) AS s FROM ev "
        "GROUP BY HOP(ts, INTERVAL '1' SECOND, INTERVAL '2' SECOND), k")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("sql-hop")
    # record@500 lands in hops [-1000,1000) and [0,2000); record@1500
    # in [0,2000) and [1000,3000)
    got = {(s, c) for (k, c, s) in sink.values}
    assert got == {(-1000, 1), (0, 2), (1000, 1)}


def test_sql_continuous_group_by():
    events = [(1, 2, 0), (1, 3, 10), (2, 5, 20)]
    env, t_env = _table_env(events)
    out = t_env.sql_query("SELECT k, SUM(u) AS s, COUNT(*) AS c "
                          "FROM ev GROUP BY k")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("sql-cont")
    # upsert semantics: one refreshed row per input; last per key wins
    last = {}
    for k, s, c in sink.values:
        last[k] = (s, c)
    assert last == {1: (5, 2), 2: (5, 1)}


def test_sql_global_aggregate():
    events = [(1, 2, 0), (2, 3, 10)]
    env, t_env = _table_env(events)
    out = t_env.sql_query("SELECT COUNT(*) AS c, AVG(u) AS a FROM ev")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("sql-global")
    assert sink.values[-1] == (2, 2.5)


def test_sql_udaf_registration():
    from flink_tpu.ops.sketches import HyperLogLogAggregate
    events = _sorted_events(n=1000, n_keys=3, n_users=200)
    env, t_env = _table_env(events)
    t_env.register_function("MY_DISTINCT",
                            lambda: HyperLogLogAggregate(precision=11))
    out = t_env.sql_query(
        "SELECT k, MY_DISTINCT(u) AS d FROM ev "
        "GROUP BY TUMBLE(ts, INTERVAL '1' SECOND), k")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("sql-udaf")
    assert sink.values and all(d > 0 for _, d in sink.values)


def test_sql_sum_distinct():
    events = [(1, 5, 0), (1, 5, 10), (1, 2, 20)]
    env, t_env = _table_env(events)
    out = t_env.sql_query(
        "SELECT k, SUM(DISTINCT u) AS s, SUM(u) AS t FROM ev "
        "GROUP BY TUMBLE(ts, INTERVAL '1' SECOND), k")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("sql-sum-distinct")
    assert sink.values == [(1, 7, 12)]


def test_sql_count_distinct_exact():
    events = [(1, 5, 0), (1, 5, 10), (1, 6, 20)]
    env, t_env = _table_env(events)
    out = t_env.sql_query(
        "SELECT k, COUNT(DISTINCT u) AS d FROM ev "
        "GROUP BY TUMBLE(ts, INTERVAL '1' SECOND), k")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("sql-distinct")
    assert sink.values == [(1, 2)]


# ---------------------------------------------------------------------
# fluent Table API
# ---------------------------------------------------------------------

def test_table_api_fluent_windowed():
    events = _sorted_events(n=300, n_keys=4)
    env, t_env = _table_env(events)
    table = t_env.scan("ev")
    out = (table.filter(col("k") < 3)
           .window(Tumble.over(1000).on("ts"))
           .group_by(col("k"))
           .select("k", "COUNT(*) AS c"))
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("table-fluent")
    expect = collections.Counter()
    for k, u, t in events:
        if k < 3:
            expect[(k, t - t % 1000)] += 1
    got_total = collections.Counter()
    for k, c in sink.values:
        got_total[k] += c
    want_total = collections.Counter()
    for (k, w), c in expect.items():
        want_total[k] += c
    assert got_total == want_total


def test_table_api_select_expressions():
    env = StreamExecutionEnvironment()
    t_env = StreamTableEnvironment.create(env)
    stream = env.from_collection([(1, 2), (3, 4)])
    table = t_env.from_data_stream(stream, ["a", "b"])
    out = table.select((col("a") + col("b")).alias("s"), "a * 2 AS d")
    sink = CollectSink()
    out.to_append_stream().add_sink(sink)
    env.execute("table-select")
    assert sorted(sink.values) == [(3, 2), (7, 6)]
    assert out.schema.fields == ["s", "d"]
