"""High availability: leader election, submitted-job recovery, leader
failover with a running job (ref: HighAvailabilityServices +
ZooKeeperLeaderElectionService + Dispatcher.java:502 recoverJobs;
JobManagerHACheckpointRecoveryITCase — SURVEY.md §4.4)."""

import os
import time

import pytest

from flink_tpu.core.functions import AggregateFunction
from flink_tpu.runtime.cluster import (
    JobManagerProcess,
    RemoteExecutor,
    TaskManagerProcess,
)
from flink_tpu.runtime.ha import (
    FileLeaderElection,
    FsSubmittedJobGraphStore,
)
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.sources import CollectSink, FromCollectionSource
from flink_tpu.streaming.windowing import Time


def test_leader_election_and_stale_lease_steal(tmp_path):
    d = str(tmp_path)
    e1 = FileLeaderElection(d, lease_timeout_s=0.4, lease_refresh_s=0.1)
    got1 = []
    e1.start("addr1:1", lambda: got1.append(1))
    deadline = time.monotonic() + 5.0
    while not e1.is_leader and time.monotonic() < deadline:
        time.sleep(0.02)
    assert e1.is_leader and got1 == [1]
    assert FileLeaderElection.current_leader_address(d) == "addr1:1"

    # a second contender stays standby while the leader is alive
    e2 = FileLeaderElection(d, lease_timeout_s=0.4, lease_refresh_s=0.1)
    got2 = []
    e2.start("addr2:2", lambda: got2.append(1))
    time.sleep(0.5)
    assert not e2.is_leader

    # simulate a CRASH: stop refreshing without releasing the lock
    e1._running = False
    time.sleep(0.1)
    deadline = time.monotonic() + 5.0
    while not e2.is_leader and time.monotonic() < deadline:
        time.sleep(0.05)
    assert e2.is_leader, "standby never stole the stale lease"
    assert FileLeaderElection.current_leader_address(d) == "addr2:2"
    e2.stop()


def test_job_graph_store_roundtrip(tmp_path):
    store = FsSubmittedJobGraphStore(str(tmp_path))
    store.put("job-a", b"blob-a", {"x": 1})
    store.put("job-b", b"blob-b", {"x": 2})
    recs = store.recover_all()
    assert {r["job_id"] for r in recs} == {"job-a", "job-b"}
    assert recs[0]["graph_blob"] in (b"blob-a", b"blob-b")
    store.remove("job-a")
    assert [r["job_id"] for r in store.recover_all()] == ["job-b"]


class SumAgg(AggregateFunction):
    def create_accumulator(self):
        return 0.0

    def add(self, value, acc):
        return acc + value[1]

    def get_result(self, acc):
        return acc

    def merge(self, a, b):
        return a + b


class HaGatedSource(FromCollectionSource):
    """Holds the tail until released (class attr, shared in-process)."""

    released = False
    HOLD = 400

    @classmethod
    def reset(cls):
        cls.released = False

    def emit_step(self, ctx, max_records):
        if not type(self).released \
                and self.offset >= len(self.items) - self.HOLD:
            time.sleep(0.002)
            return True
        return super().emit_step(ctx, max_records)


def test_dispatcher_failover_recovers_running_job(tmp_path):
    """Leader JM dies mid-job; a standby takes over, recovers the
    submitted job from the HA store, resumes it from the latest
    filesystem checkpoint on the re-registered TaskManager, and the
    client's poll follows the new leader — exactly-once counts."""
    HaGatedSource.reset()
    ha = str(tmp_path / "ha")
    cp = str(tmp_path / "checkpoints")
    jm1 = JobManagerProcess(ha_dir=ha)
    assert FileLeaderElection.wait_for_leader(ha, 10.0) == jm1.address
    tm = TaskManagerProcess(num_slots=2, ha_dir=ha)
    executor = RemoteExecutor(ha_dir=ha,
                              restart_strategy={"strategy": "fixed_delay",
                                                "restart_attempts": 10,
                                                "delay_ms": 100})
    try:
        records = [((f"k{k}", 1), i * 10)
                   for i in range(300) for k in range(5)]
        env = StreamExecutionEnvironment()
        env.enable_checkpointing(20)
        env.set_checkpoint_storage("filesystem", cp)
        (env.add_source(HaGatedSource(records, timestamped=True),
                        name="gated")
            .key_by(lambda v: v[0])
            .time_window(Time.milliseconds_of(1000))
            .aggregate(SumAgg())
            .add_sink(CollectSink()))
        env.graph.job_name = "ha-job"
        job_id = executor.submit(env.get_job_graph())

        # wait for a completed checkpoint under the OLD leader
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            status = executor._rpc.connect(
                executor._resolve(), "dispatcher"
            ).sync.request_job_status(job_id)
            if status["checkpoints_completed"] >= 1:
                break
            time.sleep(0.02)
        assert status["checkpoints_completed"] >= 1

        # CRASH the leader (no graceful lease release) and start a
        # standby that must take over
        jm1.election._running = False
        jm1.rpc.stop()
        jm2 = JobManagerProcess(ha_dir=ha)
        deadline = time.monotonic() + 20.0
        while not jm2.is_leader and time.monotonic() < deadline:
            time.sleep(0.05)
        assert jm2.is_leader

        # wait until the TM has re-registered with the new leader
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            ov = jm2.resource_manager.run_async(
                jm2.resource_manager.cluster_overview).get(5.0)
            if ov["task_executors"] >= 1:
                break
            time.sleep(0.05)
        assert ov["task_executors"] >= 1, "TM never followed the leader"

        HaGatedSource.released = True
        result = executor.wait(job_id, timeout=120.0)
        assert sum(result.accumulators["collected"]) == len(records)
        jm2.stop()
    finally:
        tm.stop()
        try:
            jm1.stop()
        except Exception:
            pass
        executor.stop()
