"""Incremental + asynchronous checkpoints (round-3, verdict item 4).

ref: RocksDBKeyedStateBackend.java:342-381 (upload only new SSTs),
SharedStateRegistry.java:42 (refcounted sharing),
CopyOnWriteStateTable.java:41-84 (processing continues while the
snapshot materializes)."""

import threading
import time

import numpy as np
import pytest

from flink_tpu.ops.device_agg import SumAggregate
from flink_tpu.runtime.checkpoints import (
    CheckpointCoordinator,
    FsCheckpointStorage,
    MemoryCheckpointStorage,
)
from flink_tpu.state.shared_registry import (
    ChunkRef,
    SharedChunk,
    SharedStateRegistry,
    content_hash,
    find_chunks,
)
from flink_tpu.streaming.log_windows import LogStructuredTumblingWindows


def _chunked_snapshot(payloads):
    return {(1, 0): {"windows": {s: SharedChunk(p)
                                 for s, p in payloads.items()}}}


def test_unchanged_chunks_cost_zero_bytes():
    """Checkpoint N+1 re-uploads nothing for unchanged chunks: the
    persisted size collapses to references."""
    storage = MemoryCheckpointStorage(retain=2)
    big = {"keys": np.arange(200_000, dtype=np.uint64)}
    size1 = storage.persist(1, {}, _chunked_snapshot({0: big}))
    size2 = storage.persist(2, {}, _chunked_snapshot({0: big}))
    assert size1 > 1_000_000          # the payload was written once
    assert size2 < 2_000              # the repeat is a reference
    # both checkpoints resolve to the full payload
    for cid in (1, 2):
        loaded = storage.load(cid)
        w = loaded["tasks"][(1, 0)]["windows"][0]
        assert np.array_equal(w["keys"], big["keys"])


def test_chunk_refcount_and_retention():
    storage = MemoryCheckpointStorage(retain=2)
    a = {"x": np.ones(1000)}
    b = {"x": np.zeros(1000)}
    storage.persist(1, {}, _chunked_snapshot({0: a}))
    storage.persist(2, {}, _chunked_snapshot({0: a, 1: b}))
    assert len(storage._chunks) == 2
    # checkpoint 3 drops chunk a's last reference once cp1 rotates out
    storage.persist(3, {}, _chunked_snapshot({1: b}))
    # cp1 evicted (retain=2); chunk a still referenced by cp2
    assert len(storage._chunks) == 2
    storage.persist(4, {}, _chunked_snapshot({1: b}))
    # cp2 evicted -> chunk a unreferenced -> deleted
    assert set(storage._chunks) == {content_hash(b)}


def test_fs_storage_chunks_and_fresh_process_recovery(tmp_path):
    d = str(tmp_path / "chk")
    storage = FsCheckpointStorage(d, retain=2)
    big = {"keys": np.arange(100_000, dtype=np.uint64)}
    size1 = storage.persist(1, {}, _chunked_snapshot({0: big}))
    size2 = storage.persist(2, {}, _chunked_snapshot({0: big}))
    assert size2 < size1 / 50
    # a FRESH storage over the same directory (process restart):
    # load resolves chunks and adopts their refs for future rotation
    s2 = FsCheckpointStorage(d, retain=2)
    loaded = s2.latest()
    w = loaded["tasks"][(1, 0)]["windows"][0]
    assert np.array_equal(w["keys"], big["keys"])
    # rotation after recovery eventually deletes the adopted chunk
    small = {"k": np.ones(10)}
    s2.persist(3, {}, _chunked_snapshot({1: small}))
    s2.persist(4, {}, _chunked_snapshot({1: small}))
    s2.persist(5, {}, _chunked_snapshot({1: small}))
    assert s2.latest()["checkpoint_id"] == 5


def test_payload_elision_requires_known_hash():
    storage = MemoryCheckpointStorage(retain=2)
    payload = {"x": np.ones(10)}
    h = content_hash(payload)
    with pytest.raises(KeyError, match="elided"):
        storage.persist(1, {}, {(1, 0): SharedChunk(None, h)})
    storage.persist(2, {}, {(1, 0): SharedChunk(payload)})
    storage.persist(3, {}, {(1, 0): SharedChunk(None, h)})  # now fine
    assert np.array_equal(storage.load(3)["tasks"][(1, 0)]["x"],
                          payload["x"])


def test_log_engine_unchanged_window_reuses_chunk_hash():
    """The log tier's per-window chunks: a window with no new records
    keeps its content hash (and skips re-hashing via the version
    cache), so consecutive checkpoints dedupe it."""
    eng = LogStructuredTumblingWindows(SumAggregate(np.float64), 1000)
    keys = np.arange(5000, dtype=np.uint64)
    eng.process_batch(keys, np.full(5000, 100), np.ones(5000))
    eng.process_batch(keys[:10], np.full(10, 1100), np.ones(10))
    s1 = eng.snapshot()
    chunks1 = {}
    for start, c in s1["windows"].items():
        chunks1[start] = c.hash
    # new data ONLY into window 1000
    eng.process_batch(keys[:5], np.full(5, 1150), np.ones(5))
    s2 = eng.snapshot()
    assert s2["windows"][0].hash == chunks1[0]          # untouched
    assert s2["windows"][1000].hash != chunks1[1000]    # grew
    # storage-level: second checkpoint re-uploads only window 1000
    storage = MemoryCheckpointStorage(retain=2)
    sz1 = storage.persist(1, {}, {(1, 0): s1})
    sz2 = storage.persist(2, {}, {(1, 0): s2})
    assert sz2 < sz1 / 10
    # and the restored engine equals a straight-through run
    restored = LogStructuredTumblingWindows(SumAggregate(np.float64), 1000)
    restored.restore(storage.load(2)["tasks"][(1, 0)])
    restored.advance_watermark(10_000)
    eng.advance_watermark(10_000)
    assert sorted(map(tuple, restored.emitted)) == \
        sorted(map(tuple, eng.emitted))


def test_keyed_backend_per_key_group_chunks_dedupe():
    """Heap/TPU backend snapshots chunk per key group; untouched key
    groups dedupe across checkpoints."""
    from flink_tpu.core.keygroups import KeyGroupRange
    from flink_tpu.core.state import ValueStateDescriptor
    from flink_tpu.state.heap_backend import HeapKeyedStateBackend
    be = HeapKeyedStateBackend(KeyGroupRange(0, 127), 128)
    desc = ValueStateDescriptor("v")
    for k in range(2000):
        be.set_current_key(k)
        be.get_partitioned_state((), desc).update(k)
    snap1 = be.snapshot()
    storage = MemoryCheckpointStorage(retain=2)
    sz1 = storage.persist(1, {}, {(1, 0): snap1})
    # touch ONE key -> only its key group's chunk changes
    be.set_current_key(7)
    be.get_partitioned_state((), desc).update(-1)
    snap2 = be.snapshot()
    sz2 = storage.persist(2, {}, {(1, 0): snap2})
    assert sz2 < sz1 / 4  # 1 kg chunk + the 128-entry ref skeleton
    changed = [h for h in
               {c.hash for c in find_chunks(snap2, [],
                                            (SharedChunk,))}
               - {c.hash for c in find_chunks(snap1, [],
                                              (SharedChunk,))}]
    assert len(changed) == 1  # exactly one key group re-uploaded


class _SlowStorage(MemoryCheckpointStorage):
    def __init__(self, delay_s):
        super().__init__(retain=2)
        self.delay_s = delay_s
        self.persist_thread_names = []

    def persist(self, checkpoint_id, metadata, task_snapshots):
        self.persist_thread_names.append(threading.current_thread().name)
        time.sleep(self.delay_s)
        return super().persist(checkpoint_id, metadata, task_snapshots)


def test_async_persist_off_barrier_path():
    """Acks complete the sync phase immediately; the write lands on
    the writer thread; notification runs after durability (2PC
    ordering) on the loop thread via drain."""
    notified = []
    storage = _SlowStorage(0.15)
    coord = CheckpointCoordinator(
        interval_ms=None, mode="exactly_once", storage=storage,
        expected_tasks={(1, 0)},
        trigger_sources=lambda cid, ts, opts: None,
        notify_complete=notified.append, async_persist=True)
    cid = coord.trigger()
    t0 = time.perf_counter()
    coord.acknowledge((1, 0), cid, {"s": 1})
    sync_elapsed = time.perf_counter() - t0
    assert sync_elapsed < 0.05          # ack path did NOT block on IO
    assert coord.completed_count == 0   # not yet durable
    assert notified == []
    st = coord.stats[cid]
    assert st.sync_duration_ms is not None and st.complete_ms is None
    coord.drain()                        # loop thread lands completion
    assert coord.completed_count == 1
    assert notified == [cid]
    assert st.complete_ms is not None
    assert st.duration_ms >= 150         # includes the slow write
    assert st.sync_duration_ms < st.duration_ms
    assert storage.persist_thread_names == ["checkpoint-writer"]


def test_async_persist_visible_after_drain_for_recovery():
    storage = _SlowStorage(0.1)
    coord = CheckpointCoordinator(
        interval_ms=None, mode="exactly_once", storage=storage,
        expected_tasks={(1, 0)},
        trigger_sources=lambda cid, ts, opts: None,
        notify_complete=lambda cid: None, async_persist=True)
    cid = coord.trigger()
    coord.acknowledge((1, 0), cid, {"s": 42})
    coord.drain()
    latest = storage.latest()
    assert latest is not None and latest["tasks"][(1, 0)]["s"] == 42


def test_async_persist_end_to_end_job(tmp_path):
    """A checkpointed job with async_persist on: completes, stats show
    the sync (ack) phase separate from the durable completion, and
    the final state restores."""
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment
    from flink_tpu.streaming.sources import CollectSink
    from flink_tpu.streaming.windowing import Time

    records = [((i % 7, 1.0), (i % 500) * 4) for i in range(30_000)]
    sink = CollectSink()
    env = StreamExecutionEnvironment()
    env.enable_checkpointing(5, async_persist=True)
    env.set_checkpoint_storage("filesystem",
                               directory=str(tmp_path / "chk"))

    class TupleSum(SumAggregate):
        def __init__(self):
            super().__init__(np.float64)

        def extract_value(self, v):
            return v[1]

    (env.from_collection(records, timestamped=True)
        .key_by(lambda t: t[0])
        .time_window(Time.seconds(2))
        .aggregate(TupleSum())
        .add_sink(sink))
    result = env.execute("async-cp")
    assert result.checkpoints_completed >= 1
    assert sum(sink.values) == 30_000
