"""Columnar zero-copy data plane: wire codec differential tests,
oversize-frame splitting, batched partition fan-out equivalence, and
router flush ordering (ref: the netty stack's SpanningRecordSerializer
/ NettyMessage framing — here the contract is "columnar and pickle
decode to identical element streams" plus "credit accounting is
invariant under frame splitting")."""

import threading

import numpy as np
import pytest

from flink_tpu.runtime import netchannel
from flink_tpu.runtime.netchannel import (
    DataClient,
    DataServer,
    decode_elements,
    encode_elements,
)
from flink_tpu.streaming.elements import (
    END_OF_STREAM,
    MAX_WATERMARK,
    CheckpointBarrier,
    StreamRecord,
    Watermark,
)


# ---------------------------------------------------------------------
# codec differential: columnar vs pickle must be indistinguishable
# ---------------------------------------------------------------------

def _roundtrip_both(batch):
    """Encode under both codec settings; decode; require identical
    streams (values, exact types, timestamps)."""
    outs = {}
    old = netchannel.COLUMNAR_ENABLED
    try:
        for flag in (True, False):
            netchannel.COLUMNAR_ENABLED = flag
            enc = encode_elements(batch)
            outs[flag] = (enc[0] if enc else "empty", decode_elements(enc))
    finally:
        netchannel.COLUMNAR_ENABLED = old
    assert outs[False][0] in ("pickle", "empty")
    for _, dec in outs.values():
        assert dec == batch
        for got, want in zip(dec, batch):
            if isinstance(want, StreamRecord):
                assert type(got.value) is type(want.value)
                if isinstance(want.value, tuple):
                    assert [type(f) for f in got.value] == \
                        [type(f) for f in want.value]
    return outs[True][0]


def test_codec_int_float_str_columns():
    assert _roundtrip_both(
        [StreamRecord(i, i * 10) for i in range(50)]) == "col"
    assert _roundtrip_both(
        [StreamRecord(i * 0.25, None) for i in range(50)]) == "col"
    assert _roundtrip_both(
        [StreamRecord(s, 7) for s in ("", "a", "héllo", "日本語", "x" * 999)]
    ) == "col"


def test_codec_tuples_of_primitives():
    batch = [StreamRecord((i, f"w{i}", i * 0.5), i * 3) for i in range(40)]
    assert _roundtrip_both(batch) == "col"
    # nested tuples: one column per field, recursively
    nested = [StreamRecord((i, (i * 2, f"n{i}")), None) for i in range(10)]
    assert _roundtrip_both(nested) == "col"


def test_codec_mixed_none_timestamps_use_validity_mask():
    batch = [StreamRecord(i, i if i % 3 else None) for i in range(30)]
    assert _roundtrip_both(batch) == "col"
    dec = decode_elements(encode_elements(batch))
    assert dec[4].timestamp == 4 and type(dec[4].timestamp) is int
    assert dec[0].timestamp is None and dec[3].timestamp is None


def test_codec_pickle_fallbacks():
    # ints beyond int64 cannot ride an i8 column
    assert _roundtrip_both([StreamRecord(2 ** 70, 1),
                            StreamRecord(-2 ** 70, 2)]) == "pickle"
    # bools must round-trip as bool, not int
    assert _roundtrip_both([StreamRecord(True, 1),
                            StreamRecord(False, 2)]) == "pickle"
    # heterogeneous value types
    assert _roundtrip_both([StreamRecord(1, 1),
                            StreamRecord("a", 2)]) == "pickle"
    # ragged tuple arity
    assert _roundtrip_both([StreamRecord((1, 2), 1),
                            StreamRecord((1,), 2)]) == "pickle"
    # lists / dicts / None values
    assert _roundtrip_both([StreamRecord([1, 2], 1)]) == "pickle"
    assert _roundtrip_both([StreamRecord(None, 1)]) == "pickle"


def test_codec_control_elements_and_empty():
    _roundtrip_both([])
    assert _roundtrip_both(
        [StreamRecord(1, 1), Watermark(5), StreamRecord(2, 6),
         CheckpointBarrier(3, 99), MAX_WATERMARK, END_OF_STREAM]
    ) == "pickle"


def test_codec_property_random_batches():
    """Randomized differential sweep: arbitrary primitive batches
    decode identically through both paths."""
    rng = np.random.default_rng(7)
    for _ in range(60):
        n = int(rng.integers(0, 40))
        kind = int(rng.integers(0, 4))
        batch = []
        for i in range(n):
            ts = int(rng.integers(-10, 10 ** 12)) \
                if rng.random() < 0.8 else None
            if kind == 0:
                v = int(rng.integers(-2 ** 62, 2 ** 62))
            elif kind == 1:
                v = float(rng.standard_normal())
            elif kind == 2:
                v = "s" * int(rng.integers(0, 20)) + str(i)
            else:
                v = (int(rng.integers(0, 99)), f"k{i % 5}",
                     float(rng.standard_normal()))
            batch.append(StreamRecord(v, ts))
        _roundtrip_both(batch)


# ---------------------------------------------------------------------
# transport: oversize batches split; credit window stays consistent
# ---------------------------------------------------------------------

class _Sink:
    """Consumer-side stand-in for `_InputChannel`."""

    def __init__(self):
        self.received = []
        self.blocked = False
        self.capacity = 1 << 30
        self.queue = self.received  # len() feeds replenish math
        self._lock = threading.Lock()

    def push(self, el):
        with self._lock:
            self.received.append(el)

    def push_batch(self, els):
        with self._lock:
            self.received.extend(els)


def _exchange(batch, capacity=1 << 20, timeout=20.0):
    """Ship `batch` through a real DataServer/DataClient TCP pair."""
    key = ("job", 0, 1, 0, 0)
    server = DataServer()
    client = DataClient()
    sink = _Sink()
    try:
        out = server.register_out_channel(key, capacity=capacity)
        client.subscribe(server.address, key, sink, capacity=capacity)
        out.push_batch(batch)
        server.wake()
        deadline = threading.Event()
        import time
        t0 = time.monotonic()
        while len(sink.received) < len(batch):
            if client.error is not None:
                raise client.error
            if time.monotonic() - t0 > timeout:
                raise AssertionError(
                    f"only {len(sink.received)}/{len(batch)} arrived")
            client.replenish_credits()
            deadline.wait(0.002)
        return list(sink.received), out
    finally:
        client.stop()
        server.stop()


def test_oversize_batch_splits_into_continuation_frames(monkeypatch):
    """A batch whose serialized size tops the frame limit ships as
    multiple `part` frames; every record arrives, in order, and the
    flow-control window never goes negative."""
    monkeypatch.setattr(netchannel, "SPLIT_FRAME_BYTES", 4096)
    netchannel.NET_STATS.reset()
    batch = [StreamRecord("x" * 64 + str(i), i) for i in range(2000)]
    received, out = _exchange(batch)
    assert received == batch
    assert netchannel.NET_STATS.frames_split > 0
    assert out.credit >= 0
    assert out.sent == len(batch)


def test_single_oversized_element_is_hard_error(monkeypatch):
    monkeypatch.setattr(netchannel, "SPLIT_FRAME_BYTES", 512)
    lock = threading.Lock()
    import socket
    a, b = socket.socketpair()
    try:
        with pytest.raises(OSError):
            netchannel.send_data_batch(
                a, lock, ("j", 0, 1, 0, 0),
                [StreamRecord("y" * 4096, 1)])
    finally:
        a.close()
        b.close()


def test_exchange_columnar_vs_pickle_identical(monkeypatch):
    batch = [StreamRecord((i, f"s{i}", i * 0.5), i) for i in range(5000)]
    got_col, _ = _exchange(batch)
    monkeypatch.setattr(netchannel, "COLUMNAR_ENABLED", False)
    got_pkl, _ = _exchange(batch)
    assert got_col == got_pkl == batch


def test_control_elements_stay_in_band_and_ordered():
    batch = ([StreamRecord(i, i) for i in range(300)]
             + [CheckpointBarrier(1, 42)]
             + [StreamRecord(i, i) for i in range(300, 600)]
             + [Watermark(599), END_OF_STREAM])
    received, _ = _exchange(batch)
    # EndOfStream defines no __eq__ (consumers isinstance-check it)
    assert received[:-1] == batch[:-1]
    assert type(received[-1]).__name__ == "EndOfStream"


# ---------------------------------------------------------------------
# batched partition fan-out: vectorized == scalar, record order kept
# ---------------------------------------------------------------------

def test_select_channels_batch_matches_scalar():
    from flink_tpu.core.functions import as_key_selector
    from flink_tpu.streaming.partitioners import (
        ForwardPartitioner,
        GlobalPartitioner,
        KeyGroupStreamPartitioner,
        RebalancePartitioner,
        RescalePartitioner,
    )

    values = ([(i % 17, i) for i in range(200)]
              + [(f"k{i % 13}", i) for i in range(200)]
              + [((i % 5, f"t{i % 3}"), i) for i in range(100)]
              + [(2 ** 66 + i, i) for i in range(20)])
    sel = as_key_selector(lambda v: v[0])

    def make():
        return [KeyGroupStreamPartitioner(sel, 128),
                RebalancePartitioner(), RescalePartitioner(),
                ForwardPartitioner(), GlobalPartitioner()]

    for num_channels in (1, 3, 7):
        for p_scalar, p_batch in zip(make(), make()):
            p_scalar.setup(num_channels)
            p_batch.setup(num_channels)
            # align RNG-seeded round-robin state
            if hasattr(p_batch, "_next"):
                p_batch._next = p_scalar._next
            want = [p_scalar.select_channels(v, num_channels)[0]
                    for v in values]
            got = p_batch.select_channels_batch(values, num_channels)
            assert got.tolist() == want, type(p_scalar).__name__


def test_routing_hashes_match_stable_hash64():
    from flink_tpu.core.keygroups import stable_hash64
    from flink_tpu.streaming.partitioners import _routing_hashes

    keys = [0, 1, -1, 2 ** 62, -(2 ** 62), 17, 2 ** 63 - 1]
    assert _routing_hashes(keys).tolist() == \
        [stable_hash64(k) for k in keys]
    keys = ["", "a", "héllo", ("x", 3), 5, -7]
    assert _routing_hashes(keys).tolist() == \
        [stable_hash64(k) for k in keys]
    # ints beyond int64 take the scalar path transparently
    keys = [2 ** 70, 5, -2 ** 70]
    assert _routing_hashes(keys).tolist() == \
        [stable_hash64(k) for k in keys]


def test_router_flush_orders_controls_after_records():
    """Buffered records flush BEFORE any control emission, so barriers
    and watermarks never overtake data on a channel."""
    from flink_tpu.runtime.local import _RouterOutput
    from flink_tpu.streaming.partitioners import RebalancePartitioner

    channels = [_Sink() for _ in range(3)]
    part = RebalancePartitioner()
    router = _RouterOutput()
    router.add_route(part, channels)
    part._next = -1
    for i in range(10):
        router.collect(StreamRecord(i, i))
    # nothing shipped yet: records sit in the router buffer
    assert sum(len(c.queue) for c in channels) == 0
    router.emit_watermark(Watermark(9))
    for ch in channels:
        q = list(ch.queue)
        assert isinstance(q[-1], Watermark)
        ts = [e.timestamp for e in q[:-1]]
        assert ts == sorted(ts)  # per-channel record order preserved
    total = sum(len(c.queue) - 1 for c in channels)
    assert total == 10
    assert router.has_queued_output() is False
