"""Differential suite for fused operator chains: one jitted columnar
program per typeflow-proven run (streaming/chain_fusion.py) must be
bit-identical to the per-operator kernel path — values, timestamps,
ts-validity masks, per-channel routing — and any failure must demote
the whole chain back to per-operator dispatch, never produce wrong
output."""

import numpy as np
import pytest

from flink_tpu.core.functions import FilterFunction, MapFunction
from flink_tpu.streaming import chain_fusion as cf
from flink_tpu.streaming.elements import RecordBatch
from flink_tpu.streaming.operators import StreamFilter, StreamMap


class _LMap(MapFunction):
    def __init__(self, fn):
        self._fn = fn

    def map(self, value):
        return self._fn(value)


class _LFilter(FilterFunction):
    def __init__(self, fn):
        self._fn = fn

    def filter(self, value):
        return self._fn(value)


class _CapOut:
    def __init__(self):
        self.batches = []

    def collect_batch(self, batch):
        self.batches.append(batch)


class _ChainOut:
    def __init__(self, op):
        self.op = op

    def collect_batch(self, batch):
        self.op.process_batch(batch)


def _mk_chain(out, map_fn=None, filter_fn=None):
    m = StreamMap(_LMap(map_fn or (lambda t: (t[0], t[1] * 3))))
    f = StreamFilter(_LFilter(filter_fn or (lambda t: (t[1] % 7) != 0)))
    m.setup(_ChainOut(f))
    f.setup(out)
    m.operator_id = "map-1"
    f.operator_id = "filter-2"
    return m, f


@pytest.fixture(autouse=True)
def _fusion_env():
    """Every test sees fusion enabled with a low row floor, and leaves
    the module flags as it found them."""
    saved = (cf.FUSION_ENABLED, cf.MIN_FUSED_ROWS,
             cf.MESH_MIN_ROWS_PER_SHARD)
    cf.FUSION_ENABLED = True
    cf.MIN_FUSED_ROWS = 256
    cf.FUSION_STATS.reset()
    yield
    (cf.FUSION_ENABLED, cf.MIN_FUSED_ROWS,
     cf.MESH_MIN_ROWS_PER_SHARD) = saved


def _assert_batches_equal(got, ref):
    assert len(got) == len(ref)
    for gb, rb in zip(got, ref):
        assert list(gb.cols) == list(rb.cols)
        for k in rb.cols:
            assert gb.cols[k].dtype == rb.cols[k].dtype, k
            assert np.array_equal(gb.cols[k], rb.cols[k],
                                  equal_nan=gb.cols[k].dtype.kind == "f"), k
        if rb.ts is None:
            assert gb.ts is None
        else:
            assert np.array_equal(gb.ts, rb.ts)
        if rb.ts_mask is None:
            assert gb.ts_mask is None
        else:
            assert np.array_equal(gb.ts_mask, rb.ts_mask)


# ---------------------------------------------------------------------
# plain mode: map + filter compaction, dtype zoo


@pytest.mark.parametrize("dtype", [
    np.int8, np.int16, np.int32, np.int64, np.uint8, np.uint32,
    np.float32, np.float64, np.bool_,
])
def test_fused_plain_bit_equality(dtype):
    rng = np.random.default_rng(3)
    vals = (rng.random(1024) * 50).astype(dtype)
    cols = {"f0": rng.integers(0, 9, 1024).astype(np.int64), "f1": vals}
    ts = rng.integers(0, 10_000, 1024).astype(np.int64)
    tsm = rng.random(1024) > 0.2

    ref_out = _CapOut()
    m1, f1 = _mk_chain(ref_out)
    m1.process_batch(RecordBatch({k: v.copy() for k, v in cols.items()},
                                 ts.copy(), tsm.copy()))

    fused_out = _CapOut()
    m2, f2 = _mk_chain(fused_out)
    prog = cf.compile_chain([m2, f2])
    assert prog is not None
    batch = RecordBatch(dict(cols), ts.copy(), tsm.copy())
    assert prog.wants(batch)
    prog.run(batch)
    assert prog.active, prog.demoted_reason
    _assert_batches_equal(fused_out.batches, ref_out.batches)
    # accounting parity: fused rows count into the columnar totals the
    # per-operator kernels would have reported
    assert (m2.columnar_rows, f2.columnar_rows) == \
        (m1.columnar_rows, f1.columnar_rows)
    assert m2.fused_rows == 1024
    assert m2.columnar_decided_by == "fused"


def test_small_batches_stay_per_operator():
    cols = {"f0": np.arange(64, dtype=np.int64),
            "f1": np.arange(64, dtype=np.int64)}
    out = _CapOut()
    m, f = _mk_chain(out)
    prog = cf.compile_chain([m, f])
    assert prog is not None
    assert not prog.wants(RecordBatch(dict(cols)))
    assert prog.active


# ---------------------------------------------------------------------
# routed mode: fused splitmix64 + channel compaction vs split_batch


def test_fused_routing_matches_split_batch():
    from flink_tpu.core.functions import _FieldKeySelector
    from flink_tpu.streaming.partitioners import KeyGroupStreamPartitioner

    class _Ch:
        def __init__(self):
            self.got = []

        def push(self, element):
            self.got.append(element)

    class _Router:
        def __init__(self, part, channels):
            self.routes = [(part, channels, None)]
            self.records_out_counter = None

        def flush_records(self):
            pass

        def collect_batch(self, batch):
            for part, channels, _tag in self.routes:
                for idx, sub in part.split_batch(batch, len(channels)):
                    channels[idx].push(sub)

    rng = np.random.default_rng(7)
    n = 1500
    cols = {"f0": rng.integers(0, 100, n).astype(np.int64),
            "f1": rng.integers(-50, 50, n).astype(np.int64)}
    ts = rng.integers(0, 10_000, n).astype(np.int64)
    nch = 4

    ref_chs = [_Ch() for _ in range(nch)]
    ref_router = _Router(
        KeyGroupStreamPartitioner(_FieldKeySelector(0), 128), ref_chs)
    m1, f1 = _mk_chain(ref_router)
    m1.process_batch(RecordBatch({k: v.copy() for k, v in cols.items()},
                                 ts.copy()))

    fu_chs = [_Ch() for _ in range(nch)]
    fu_router = _Router(
        KeyGroupStreamPartitioner(_FieldKeySelector(0), 128), fu_chs)
    m2, f2 = _mk_chain(fu_router)
    prog = cf.compile_chain([m2, f2], router=fu_router)
    assert prog is not None and prog.route_field == 0
    prog.run(RecordBatch(dict(cols), ts.copy()))
    assert prog.active, prog.demoted_reason
    for c in range(nch):
        _assert_batches_equal(fu_chs[c].got, ref_chs[c].got)


def test_precomputed_routing_hashes_match_per_row():
    """The device splitmix64 twin must be bit-identical to the numpy
    hash the per-row routing path uses, so precomputed batch.routing
    lands every row on the same channel."""
    from flink_tpu.core.keygroups import splitmix64_np

    keys = np.array([0, 1, -7, 2**40, -2**40, 12345], np.int64)
    from flink_tpu.streaming.chain_fusion import _jnp_splitmix64
    pytest.importorskip("jax")
    import jax
    from jax.experimental import enable_x64
    with enable_x64():
        dev = np.asarray(jax.jit(_jnp_splitmix64)(
            jax.device_put(keys.view(np.uint64))))
    assert np.array_equal(dev, splitmix64_np(keys.view(np.uint64)))


# ---------------------------------------------------------------------
# window mode: fused pane assignment through the harness


@pytest.mark.parametrize("kind", ["tumbling", "sliding"])
def test_fused_window_differential(kind):
    from flink_tpu.core.state import AggregatingStateDescriptor
    from flink_tpu.ops.device_agg import SumAggregate
    from flink_tpu.streaming.harness import OneInputStreamOperatorTestHarness
    from flink_tpu.streaming.window_operator import WindowOperator
    from flink_tpu.streaming.windowing import (
        SlidingEventTimeWindows,
        TumblingEventTimeWindows,
    )

    class _KVSum(SumAggregate):
        def __init__(self):
            super().__init__(np.float64)

        def extract_value(self, value):
            return value[1] if isinstance(value, tuple) else value

    def run(fused):
        descriptor = AggregatingStateDescriptor("w-sum", _KVSum())

        def wfn(key, window, elements):
            for v in elements:
                yield (key, float(v), window.start)

        assigner = (TumblingEventTimeWindows.of(100) if kind == "tumbling"
                    else SlidingEventTimeWindows.of(200, 100))
        wop = WindowOperator(assigner, descriptor, window_function=wfn,
                             allowed_lateness=0)
        h = OneInputStreamOperatorTestHarness(
            wop, key_selector=lambda x: x[0], state_backend="heap")
        h.open()
        m, f = _mk_chain(_ChainOut(wop),
                         map_fn=lambda t: (t[0], t[1] * 3.0))
        prog = cf.compile_chain([m, f, wop]) if fused else None
        if fused:
            assert prog is not None and prog.window_op is wop
        out = []
        rng = np.random.default_rng(5)
        for c in range(4):
            n = 800
            batch = RecordBatch(
                {"f0": rng.integers(0, 7, n).astype(np.int64),
                 "f1": rng.integers(0, 50, n).astype(np.int64)},
                rng.integers(max(0, c * 300 - 150), c * 300 + 300,
                             n).astype(np.int64))
            if fused and prog.wants(batch):
                prog.run(batch)
                assert prog.active, prog.demoted_reason
            else:
                m.process_batch(batch)
            h.process_watermark(c * 300)
            out.extend((r.value, r.timestamp) for r in h.get_output())
            h.clear_output()
        h.process_watermark(10 ** 13)
        out.extend((r.value, r.timestamp) for r in h.get_output())
        return out

    ref = run(fused=False)
    got = run(fused=True)
    assert ref
    assert got == ref


# ---------------------------------------------------------------------
# mesh variant


def test_fused_mesh_variant_bit_exact():
    cf.MESH_MIN_ROWS_PER_SHARD = 64  # force the sharded program
    rng = np.random.default_rng(11)
    n = 5000
    cols = {"f0": rng.integers(0, 100, n).astype(np.int64),
            "f1": rng.integers(-50, 50, n).astype(np.int64)}
    ts = rng.integers(0, 10_000, n).astype(np.int64)
    tsm = rng.random(n) > 0.1

    ref_out = _CapOut()
    m1, _f1 = _mk_chain(ref_out)
    m1.process_batch(RecordBatch({k: v.copy() for k, v in cols.items()},
                                 ts.copy(), tsm.copy()))

    fused_out = _CapOut()
    m2, f2 = _mk_chain(fused_out)
    prog = cf.compile_chain([m2, f2])
    assert prog is not None
    assert prog.mesh_shards > 1, "conftest forces 8 virtual devices"
    prog.run(RecordBatch(dict(cols), ts.copy(), tsm.copy()))
    assert prog.active, prog.demoted_reason
    _assert_batches_equal(fused_out.batches, ref_out.batches)


def test_fused_mesh_route_matches_split_batch():
    """Routing on the mesh: per-shard partitions merged channel-major
    on the host must reproduce split_batch's global stable order
    bit-for-bit on every channel."""
    from flink_tpu.core.functions import _FieldKeySelector
    from flink_tpu.streaming.partitioners import KeyGroupStreamPartitioner

    class _Ch:
        def __init__(self):
            self.got = []

        def push(self, element):
            self.got.append(element)

    class _Router:
        def __init__(self, part, channels):
            self.routes = [(part, channels, None)]
            self.records_out_counter = None

        def flush_records(self):
            pass

        def collect_batch(self, batch):
            for part, channels, _tag in self.routes:
                for idx, sub in part.split_batch(batch, len(channels)):
                    channels[idx].push(sub)

    cf.MESH_MIN_ROWS_PER_SHARD = 64  # force the sharded program
    rng = np.random.default_rng(17)
    n = 4096
    cols = {"f0": rng.integers(0, 100, n).astype(np.int64),
            "f1": rng.integers(-50, 50, n).astype(np.int64)}
    ts = rng.integers(0, 10_000, n).astype(np.int64)
    nch = 4

    ref_chs = [_Ch() for _ in range(nch)]
    ref_router = _Router(
        KeyGroupStreamPartitioner(_FieldKeySelector(0), 128), ref_chs)
    m1, _f1 = _mk_chain(ref_router)
    m1.process_batch(RecordBatch({k: v.copy() for k, v in cols.items()},
                                 ts.copy()))

    fu_chs = [_Ch() for _ in range(nch)]
    fu_router = _Router(
        KeyGroupStreamPartitioner(_FieldKeySelector(0), 128), fu_chs)
    m2, _f2 = _mk_chain(fu_router)
    prog = cf.compile_chain([m2, _f2], router=fu_router)
    assert prog is not None and prog.route_field == 0
    assert prog.mesh_shards > 1, "conftest forces 8 virtual devices"
    prog.run(RecordBatch(dict(cols), ts.copy()))
    assert prog.active, prog.demoted_reason
    assert ("route", False, True) in prog._fns, \
        "the batch must have taken the sharded route program"
    for c in range(nch):
        _assert_batches_equal(fu_chs[c].got, ref_chs[c].got)


# ---------------------------------------------------------------------
# demotion: any kernel failure locks the chain boxed with a reason


def test_probe_failure_demotes_whole_chain():
    out = _CapOut()
    m, f = _mk_chain(out)
    prog = cf.compile_chain([m, f])
    assert prog is not None
    bad = RecordBatch({"f0": np.array(["a", "b"] * 300, dtype=object),
                       "f1": np.arange(600, dtype=np.int64)})
    assert prog.wants(bad)
    prog.run(bad)
    assert not prog.active
    assert prog.demoted_reason
    assert cf.FUSION_STATS.last_demotion is not None
    assert cf.FUSION_STATS.last_demotion[0] == prog.label
    # the failing batch replayed through the per-operator path
    assert m.columnar_rows + m.boxed_rows == 600
    assert m.fused_rows == 0
    # demotion resets the introspection verdicts
    from flink_tpu.analysis.columnar_eligibility import operator_decided_by
    assert operator_decided_by(m) != "fused"
    assert m._fused_member is None
    # the chain stays demoted: later clean batches go per-operator
    good = RecordBatch({"f0": np.arange(600, dtype=np.int64),
                        "f1": np.arange(600, dtype=np.int64)})
    assert not prog.wants(good)
    m.process_batch(good)
    assert out.batches, "per-operator path must keep flowing"


def test_demoted_output_matches_per_operator():
    """The batch that triggers demotion must still produce exactly the
    per-operator output (replayed, nothing emitted twice)."""
    out = _CapOut()
    m, f = _mk_chain(out)
    prog = cf.compile_chain([m, f])
    bad = RecordBatch({"f0": np.array(["x"] * 600, dtype=object),
                       "f1": np.arange(600, dtype=np.int64)})
    prog.run(bad)

    ref_out = _CapOut()
    m2, f2 = _mk_chain(ref_out)
    m2.process_batch(RecordBatch(
        {"f0": np.array(["x"] * 600, dtype=object),
         "f1": np.arange(600, dtype=np.int64)}))
    assert len(out.batches) == len(ref_out.batches)
    for gb, rb in zip(out.batches, ref_out.batches):
        for k in rb.cols:
            assert np.array_equal(gb.cols[k], rb.cols[k])


# ---------------------------------------------------------------------
# introspection: reports + kernel table


def test_chain_report_carries_fusion_verdict():
    from flink_tpu.analysis.columnar_eligibility import chain_report

    m, f = _mk_chain(_CapOut())
    rep = chain_report([m, f])
    assert rep["fusion"]["fusable"]
    assert rep["fusion"]["fused_ops"] == ["map-1", "filter-2"]
    assert rep["fusion"]["first_blocker"] is None

    class _Opaque(MapFunction):
        def map(self, value):
            return hash(repr(value))  # not liftable

    blocked = StreamMap(_Opaque())
    blocked.setup(_CapOut())
    blocked.operator_id = "opaque-3"
    rep = chain_report([m, f, blocked])
    assert rep["fusion"]["fusable"]
    assert rep["fusion"]["first_blocker"] == "opaque-3"
    assert rep["fusion"]["blocker_reason"]


def test_fused_kernel_label_reaches_device_ledger():
    from flink_tpu.runtime.device_stats import TELEMETRY

    out = _CapOut()
    m, f = _mk_chain(out)
    prog = cf.compile_chain([m, f])
    cols = {"f0": np.arange(1024, dtype=np.int64),
            "f1": np.arange(1024, dtype=np.int64)}
    TELEMETRY.enabled = True
    TELEMETRY.reset()
    try:
        prog.run(RecordBatch(dict(cols)))
        payload = TELEMETRY.payload()
    finally:
        TELEMETRY.enabled = False
    assert prog.active, prog.demoted_reason
    assert prog.label in payload["kernels"]
    assert payload["kernels"][prog.label]["dispatches"] >= 1
    # inside the fused region the only boundary crossings are the
    # chain's own in/out transfers — no per-operator intermediates
    transfer_tags = {t.split(".", 1)[1] for t in payload["transfers"]}
    assert transfer_tags == {"chain.boundary"}


# ---------------------------------------------------------------------
# exactly-once: chaos run with barriers straddling fused batches


def test_chaos_exactly_once_with_fused_chain():
    import collections
    import tempfile

    from flink_tpu.runtime import faults
    from flink_tpu.runtime.faults import FaultInjector
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment

    rng = np.random.default_rng(17)
    data = [((int(k), int(v)), int(t)) for t, (k, v) in enumerate(
        zip(rng.integers(0, 7, 4000), rng.integers(0, 100, 4000)))]

    def run():
        from flink_tpu.core.functions import AggregateFunction
        from flink_tpu.streaming.columnar import VectorizedCollectionSource
        from flink_tpu.streaming.sources import CollectSink
        from flink_tpu.streaming.windowing import Time

        class SumAgg(AggregateFunction):
            def create_accumulator(self):
                return 0

            def add(self, value, acc):
                return acc + value[1]

            def get_result(self, acc):
                return acc

            def merge(self, a, b):
                return a + b

        sink = CollectSink()
        env = StreamExecutionEnvironment()
        env.enable_checkpointing(10, tolerable_failures=16)
        env.set_checkpoint_storage(
            "filesystem",
            directory=tempfile.mkdtemp(prefix="flink_tpu_fusedchaos_"))
        env.set_restart_strategy("fixed_delay", restart_attempts=5,
                                 delay_ms=0)
        (env.add_source(VectorizedCollectionSource(data, timestamped=True,
                                                   chunk=512))
            .map(lambda t: (t[0], t[1] * 3))
            .filter(lambda t: t[1] % 7 != 0)
            .key_by(0)
            .time_window(Time.milliseconds_of(100))
            .aggregate(SumAgg())
            .add_sink(sink))
        before = cf.FUSION_STATS.fused_batches
        result = env.execute("fused-chaos")
        engaged = cf.FUSION_STATS.fused_batches - before
        return collections.Counter(sink.values), result, engaged

    faults.deactivate()
    baseline, _, engaged = run()
    assert engaged > 0, "the fused chain must actually run"
    inj = FaultInjector(seed=13)
    inj.fail_n_times("storage.persist", 1)
    inj.fail_n_times("task.process", 1, after=4)
    inj.delay("task.process", 2)
    faults.install(inj)
    try:
        chaos, result, engaged = run()
    finally:
        faults.deactivate()
    assert result.restarts >= 1, "the injected crash must have fired"
    assert engaged > 0, "replayed batches must ride the fused chain too"
    assert chaos == baseline
    assert cf.FUSION_STATS.demotions == 0


# ---------------------------------------------------------------------
# end-to-end: fused and unfused executions of the same job are equal


@pytest.mark.parametrize("keyer", ["field", "lambda"])
def test_e2e_fused_matches_unfused(keyer):
    from flink_tpu.core.functions import AggregateFunction
    from flink_tpu.streaming.columnar import VectorizedCollectionSource
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment
    from flink_tpu.streaming.sources import CollectSink
    from flink_tpu.streaming.windowing import Time

    rng = np.random.default_rng(11)
    data = [((int(k), int(v)), int(t)) for t, (k, v) in enumerate(
        zip(rng.integers(0, 7, 3000), rng.integers(0, 100, 3000)))]

    class SumAgg(AggregateFunction):
        def create_accumulator(self):
            return 0

        def add(self, value, acc):
            return acc + value[1]

        def get_result(self, acc):
            return acc

        def merge(self, a, b):
            return a + b

    def run(fused):
        sink = CollectSink()
        env = StreamExecutionEnvironment()
        (env.add_source(VectorizedCollectionSource(data, timestamped=True,
                                                   chunk=512))
            .map(lambda t: (t[0], t[1] * 3))
            .filter(lambda t: t[1] % 7 != 0)
            .key_by(0 if keyer == "field" else (lambda v: v[0]))
            .time_window(Time.milliseconds_of(100))
            .aggregate(SumAgg())
            .add_sink(sink))
        saved = cf.FUSION_ENABLED
        cf.FUSION_ENABLED = fused
        before = cf.FUSION_STATS.fused_batches
        try:
            env.execute("fusion-e2e")
        finally:
            cf.FUSION_ENABLED = saved
        return sorted(sink.values), cf.FUSION_STATS.fused_batches - before

    ref, engaged_off = run(fused=False)
    got, engaged_on = run(fused=True)
    assert engaged_off == 0
    assert engaged_on > 0
    assert ref
    assert got == ref
    assert cf.FUSION_STATS.demotions == 0
