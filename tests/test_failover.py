"""Failover strategies (ref: failover/RestartPipelinedRegionStrategy
.java, FailoverRegion.java): region computation + region-scoped
restart on the local executor."""

import time

import pytest

from flink_tpu.core.functions import MapFunction, RichFunction
from flink_tpu.runtime.failover import (
    compute_pipelined_regions,
    pointwise_targets,
    region_of,
)
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.sources import FromCollectionSource, SinkFunction


class NullSink(SinkFunction):
    def invoke(self, value, context=None):
        pass


# ---------------------------------------------------------------------
# region analysis
# ---------------------------------------------------------------------

def _graph_of(env):
    return env.get_job_graph()


def test_pointwise_job_splits_into_regions():
    env = StreamExecutionEnvironment()
    env.set_parallelism(3)
    (env.add_source(FromCollectionSource([1, 2, 3]), parallelism=3)
        .map(lambda v: v, name="m")
        .add_sink(NullSink()))
    regions = compute_pipelined_regions(_graph_of(env))
    assert len(regions) == 3
    for region in regions:
        # each slice: one subtask of every (possibly chained) vertex
        indices = {idx for _, idx in region}
        assert len(indices) == 1


def test_all_to_all_job_is_one_region():
    env = StreamExecutionEnvironment()
    env.set_parallelism(3)
    (env.add_source(FromCollectionSource([(1, 1)]), parallelism=3)
        .key_by(lambda v: v[0])
        .map(lambda v: v, name="m")
        .add_sink(NullSink()))
    regions = compute_pipelined_regions(_graph_of(env))
    assert len(regions) == 1


def test_pointwise_targets_rules():
    assert pointwise_targets(0, 2, 4) == [0, 1]
    assert pointwise_targets(1, 2, 4) == [2, 3]
    assert pointwise_targets(3, 4, 2) == [1]


def test_region_of_unknown_key_scopes_everything():
    regions = [frozenset({(1, 0)}), frozenset({(1, 1)})]
    assert region_of(regions, (9, 9)) == {(1, 0), (1, 1)}


# ---------------------------------------------------------------------
# region-scoped restart on the local executor
# ---------------------------------------------------------------------

class ShardedGatedSource(FromCollectionSource, RichFunction):
    """Parallel source: each subtask takes its index-strided shard;
    trickles its tail until the poison has been consumed."""

    poison_done = False

    def __init__(self, items):
        FromCollectionSource.__init__(self, items, timestamped=False)
        RichFunction.__init__(self)
        self._sharded = False

    def open(self, configuration=None):
        ctx = self._runtime_context
        if not self._sharded:
            self.items = self.items[
                ctx.index_of_this_subtask::
                ctx.number_of_parallel_subtasks]
            self._sharded = True

    def emit_step(self, ctx, max_records):
        if not type(self).poison_done \
                and self.offset >= max(len(self.items) - 40, 0):
            if self.offset >= len(self.items):
                return False
            time.sleep(0.001)
            return super().emit_step(ctx, 1)
        return super().emit_step(ctx, max_records)


class PoisonOnceMap(MapFunction):
    armed = True

    def map(self, value):
        # write through the BASE class explicitly: type(self) would
        # shadow the flag on a subclass
        if value == "POISON" and PoisonOnceMap.armed:
            PoisonOnceMap.armed = False
            raise RuntimeError("poisoned")
        return value


class SetSink(SinkFunction):
    """Set-dedup collection (region replay may re-emit records the
    previous sink instance already saw — same-sink dedup is the
    idempotent-sink pattern)."""

    collected = set()

    def invoke(self, value, context=None):
        type(self).collected.add(value)

    def accumulators(self):
        return {"set": list(type(self).collected)}


def _run_failover_job(strategy):
    PoisonOnceMap.armed = True
    SetSink.collected = set()
    ShardedGatedSource.poison_done = False
    items = [f"a{i}" for i in range(400)] + ["POISON"] \
        + [f"b{i}" for i in range(399)]
    # index-strided sharding puts POISON (index 400) on subtask 0 of 2
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    env.enable_checkpointing(10)
    env.set_restart_strategy("fixed_delay", restart_attempts=3, delay_ms=0)
    env.set_failover_strategy(strategy)
    (env.add_source(ShardedGatedSource(items), parallelism=2)
        .map(PoisonOnceMap(), name="poisoner")
        .add_sink(SetSink()))
    client = env.execute_async(f"{strategy}-failover")
    deadline = time.monotonic() + 30.0
    while PoisonOnceMap.armed and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not PoisonOnceMap.armed, "poison never tripped"
    ShardedGatedSource.poison_done = True
    result = client.wait(60.0)
    assert result.restarts == 1
    # every record delivered (sets dedupe the failed region's replay)
    assert SetSink.collected == set(items)
    return result


def test_region_failover_scopes_restart_to_failed_slice():
    result = _run_failover_job("region")
    # the restart was region-scoped: the healthy slice carried its
    # live state instead of rolling back to the checkpoint
    assert result.region_restarts == 1


def test_full_failover_restarts_everything():
    result = _run_failover_job("full")
    assert result.region_restarts == 0

def _pointwise_regions():
    env = StreamExecutionEnvironment()
    env.set_parallelism(3)
    (env.add_source(FromCollectionSource([1, 2, 3]), parallelism=3)
        .map(lambda v: v, name="m")
        .add_sink(NullSink()))
    return compute_pipelined_regions(_graph_of(env))


def test_region_index_matches_linear_scan():
    """build_region_index is a pure lookup accelerator: indexed and
    linear region_of agree for every subtask."""
    from flink_tpu.runtime.failover import build_region_index

    regions = _pointwise_regions()
    index = build_region_index(regions)
    for region in regions:
        for key in region:
            assert region_of(regions, key, index) == \
                region_of(regions, key)
            assert region_of(regions, key, index) is index[key]


def test_region_of_unknown_key_with_index_scopes_everything():
    """Regression: an unattributed failure (a task_key the index does
    not know) must still scope to the union of all regions — a full
    restart — exactly as the linear path does."""
    from flink_tpu.runtime.failover import build_region_index

    regions = _pointwise_regions()
    index = build_region_index(regions)
    everything = frozenset().union(*regions)
    assert region_of(regions, (99, 99), index) == everything
    assert region_of(regions, (99, 99)) == everything
