"""REST web monitor + CLI front end (ref: RestServerEndpoint /
WebMonitorEndpoint and CliFrontend — SURVEY.md §2.2/§2.7)."""

import json
import time
import urllib.request

from flink_tpu.cli import main as cli_main
from flink_tpu.runtime.rest import WebMonitor
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.sources import CollectSink, SourceFunction


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        ctype = r.headers.get("Content-Type", "")
        body = r.read().decode()
    return (json.loads(body) if "json" in ctype else body), ctype


def test_monitor_serves_metrics_and_jobs():
    class Slowish(SourceFunction):
        def __init__(self):
            self._running = True

        def run(self, ctx):
            for i in range(2000):
                if not self._running:
                    return
                ctx.collect(i)
                time.sleep(0.0005)

        def cancel(self):
            self._running = False

    env = StreamExecutionEnvironment()
    env.enable_checkpointing(20)
    sink = CollectSink()
    env.add_source(Slowish()).map(lambda v: v + 1).add_sink(sink)
    client = env.execute_async("monitored-job")

    monitor = WebMonitor(env.get_metric_registry()).start()
    try:
        monitor.track_job("monitored-job", client)
        time.sleep(0.3)
        jobs, _ = _get(monitor.port, "/jobs")
        assert jobs["monitored-job"]["status"] == "RUNNING"
        metrics, _ = _get(monitor.port, "/metrics")
        assert any("numRecordsIn" in k for k in metrics)
        scoped, _ = _get(monitor.port, "/jobs/monitored-job/metrics")
        assert scoped and all(k.startswith("monitored-job.")
                              for k in scoped)
        text, ctype = _get(monitor.port, "/metrics/prometheus")
        assert "flink_tpu_" in text and "text/plain" in ctype
        client.cancel()
        client.wait(timeout=10)
        status, _ = _get(monitor.port, "/jobs/monitored-job")
        assert status["status"] == "CANCELED"
        try:
            _get(monitor.port, "/jobs/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        monitor.stop()


def test_cli_info_and_run(tmp_path, capsys):
    assert cli_main(["info"]) == 0
    out = capsys.readouterr().out
    assert "flink_tpu" in out

    script = tmp_path / "job.py"
    script.write_text(
        "from flink_tpu.batch import ExecutionEnvironment\n"
        "env = ExecutionEnvironment.get_execution_environment()\n"
        "print(sum(env.from_collection(range(10)).collect()))\n")
    assert cli_main(["run", str(script)]) == 0
    assert "45" in capsys.readouterr().out
    assert cli_main(["nope"]) == 2
    assert cli_main([]) == 0
