"""REST web monitor + CLI front end (ref: RestServerEndpoint /
WebMonitorEndpoint and CliFrontend — SURVEY.md §2.2/§2.7)."""

import json
import time
import urllib.request

from flink_tpu.cli import main as cli_main
from flink_tpu.runtime.rest import WebMonitor
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.sources import CollectSink, SourceFunction


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        ctype = r.headers.get("Content-Type", "")
        body = r.read().decode()
    return (json.loads(body) if "json" in ctype else body), ctype


def test_monitor_serves_metrics_and_jobs():
    class Slowish(SourceFunction):
        def __init__(self):
            self._running = True

        def run(self, ctx):
            for i in range(2000):
                if not self._running:
                    return
                ctx.collect(i)
                time.sleep(0.0005)

        def cancel(self):
            self._running = False

    env = StreamExecutionEnvironment()
    env.enable_checkpointing(20)
    sink = CollectSink()
    env.add_source(Slowish()).map(lambda v: v + 1).add_sink(sink)
    client = env.execute_async("monitored-job")

    monitor = WebMonitor(env.get_metric_registry()).start()
    try:
        monitor.track_job("monitored-job", client)
        time.sleep(0.3)
        jobs, _ = _get(monitor.port, "/jobs")
        assert jobs["monitored-job"]["status"] == "RUNNING"
        metrics, _ = _get(monitor.port, "/metrics")
        assert any("numRecordsIn" in k for k in metrics)
        scoped, _ = _get(monitor.port, "/jobs/monitored-job/metrics")
        assert scoped and all(k.startswith("monitored-job.")
                              for k in scoped)
        text, ctype = _get(monitor.port, "/metrics/prometheus")
        assert "flink_tpu_" in text and "text/plain" in ctype
        client.cancel()
        client.wait(timeout=10)
        status, _ = _get(monitor.port, "/jobs/monitored-job")
        assert status["status"] == "CANCELED"
        try:
            _get(monitor.port, "/jobs/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        monitor.stop()


def test_cli_info_and_run(tmp_path, capsys):
    assert cli_main(["info"]) == 0
    out = capsys.readouterr().out
    assert "flink_tpu" in out

    script = tmp_path / "job.py"
    script.write_text(
        "from flink_tpu.batch import ExecutionEnvironment\n"
        "env = ExecutionEnvironment.get_execution_environment()\n"
        "print(sum(env.from_collection(range(10)).collect()))\n")
    assert cli_main(["run", str(script)]) == 0
    assert "45" in capsys.readouterr().out
    assert cli_main(["nope"]) == 2
    assert cli_main([]) == 0


# ---------------------------------------------------------------------
# ops verbs against a live cluster: run -> list -> savepoint ->
# cancel [-s] (ref: CliFrontend.java list/savepoint/cancel/stop)
# ---------------------------------------------------------------------

def test_cli_ops_verbs_against_live_cluster(tmp_path, capsys):
    import numpy as np

    from flink_tpu.runtime.cluster import (
        JobManagerProcess,
        TaskManagerProcess,
    )

    class GatedSource(SourceFunction):
        """Emits 2000 records, then idles until released (class gate)
        — keeps the job alive while the test drives the ops verbs."""

        released = False
        HOLD_AT = 2000

        def __init__(self, n=8000):
            self.n = n
            self.offset = 0
            self._running = True

        def run(self, ctx):
            while self.emit_step(ctx, 64):
                pass

        def emit_step(self, ctx, max_records):
            from flink_tpu.streaming.elements import MAX_WATERMARK
            if not self._running:
                return False
            if not type(self).released \
                    and self.offset >= type(self).HOLD_AT:
                time.sleep(0.002)
                return True
            end = min(self.offset + max_records, self.n)
            for i in range(self.offset, end):
                ctx.collect_with_timestamp((i % 5, 1.0), i)
            self.offset = end
            if self.offset >= self.n:
                ctx.emit_watermark(MAX_WATERMARK)
                return False
            return True

        def cancel(self):
            self._running = False

        def snapshot_function_state(self, checkpoint_id=None):
            return {"offset": self.offset}

        def restore_function_state(self, state):
            self.offset = state["offset"]

    jm = JobManagerProcess()
    tm = TaskManagerProcess(jm.address, num_slots=2, tm_id="cli-tm")
    executor = None
    try:
        env = StreamExecutionEnvironment()
        env.use_remote_cluster(jm.address)
        env.enable_checkpointing(20)
        (env.add_source(GatedSource(), name="gated")
            .map(lambda v: v)
            .add_sink(CollectSink()))
        executor = env._make_executor()
        job_id = executor.submit(env.get_job_graph())

        # wait until RUNNING with >= 1 checkpoint (savepoint needs a
        # live coordinator)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            st = jm.dispatcher.run_async(
                jm.dispatcher.request_job_status, job_id).get(5.0)
            if st["state"] == "RUNNING" \
                    and st["checkpoints_completed"] >= 1:
                break
            time.sleep(0.02)
        assert st["state"] == "RUNNING", st

        # list: the job shows as RUNNING
        assert cli_main(["list", "--master", jm.address]) == 0
        out = capsys.readouterr().out
        assert job_id in out and "RUNNING" in out

        # savepoint: triggers + completes, file exists
        spdir = str(tmp_path / "sp")
        assert cli_main(["savepoint", "--master", jm.address,
                         job_id, spdir]) == 0
        out = capsys.readouterr().out
        assert "savepoint written to" in out
        path = out.split("savepoint written to ", 1)[1].strip()
        import os
        assert os.path.exists(path)

        # cancel -s: savepoint then cancel; job goes terminal
        sp2 = str(tmp_path / "sp2")
        assert cli_main(["cancel", "--master", jm.address, job_id,
                         "-s", sp2]) == 0
        out = capsys.readouterr().out
        assert "cancelled" in out
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            st = jm.dispatcher.run_async(
                jm.dispatcher.request_job_status, job_id).get(5.0)
            if st["state"] in ("CANCELED", "FINISHED", "FAILED"):
                break
            time.sleep(0.02)
        assert st["state"] == "CANCELED", st

        # list --all shows the terminal job
        assert cli_main(["list", "--master", jm.address, "--all"]) == 0
        out = capsys.readouterr().out
        assert job_id in out and "CANCELED" in out

        # restore from the cancel -s savepoint finishes the stream
        # exactly-once (the savepoint is genuinely usable)
        sp2_files = os.listdir(sp2)
        assert sp2_files, "cancel -s left no savepoint file"
        env2 = StreamExecutionEnvironment()
        env2.use_remote_cluster(jm.address)
        env2.enable_checkpointing(20)
        env2.set_savepoint_restore(os.path.join(sp2, sp2_files[0]))
        GatedSource.released = True
        sink2 = CollectSink()
        (env2.add_source(GatedSource(), name="gated")
            .map(lambda v: v)
            .add_sink(sink2))
        result = env2.execute("resume-from-cancel-s")
        collected = result.accumulators["collected"]
        total = sum(v[1] for v in collected)
        offset_restored = 8000 - len(collected)
        assert total == len(collected) and offset_restored >= 0
    finally:
        GatedSource.released = False  # class gate: re-runs start held
        if executor is not None:
            executor.stop()
        tm.stop()
        jm.stop()


def test_dashboard_page_and_job_detail(tmp_path):
    """/web serves the dashboard; /jobs/<name>/detail carries
    vertices, checkpoint stats, and backpressure for a live job
    (ref: flink-runtime-web, scaled to one static page)."""
    from flink_tpu.runtime.metrics import MetricRegistry

    class Trickle(SourceFunction):
        def __init__(self, n=4000):
            self.n = n
            self.offset = 0
            self._running = True

        def run(self, ctx):
            while self.emit_step(ctx, 64):
                pass

        def emit_step(self, ctx, max_records):
            from flink_tpu.streaming.elements import MAX_WATERMARK
            if not self._running:
                return False
            end = min(self.offset + max_records, self.n)
            for i in range(self.offset, end):
                ctx.collect_with_timestamp((i % 3, 1.0), i)
            self.offset = end
            time.sleep(0.001)
            if self.offset >= self.n:
                ctx.emit_watermark(MAX_WATERMARK)
                return False
            return True

        def cancel(self):
            self._running = False

    registry = MetricRegistry()
    monitor = WebMonitor(registry).start()
    try:
        env = StreamExecutionEnvironment()
        env.enable_checkpointing(10)
        (env.add_source(Trickle(), name="trickle")
            .map(lambda v: v, name="ident")
            .add_sink(CollectSink()))
        client = env.execute_async("dash-job")
        monitor.track_job("dash-job", client)

        html, ctype = _get(monitor.port, "/web")
        assert "text/html" in ctype
        assert "flink_tpu dashboard" in html and "/detail" in html

        deadline = time.time() + 20
        detail = {}
        while time.time() < deadline:
            detail, _ = _get(monitor.port, "/jobs/dash-job/detail")
            if detail.get("vertices") \
                    and detail["checkpoints"]["completed"] >= 1:
                break
            time.sleep(0.05)
        assert detail["status"] in ("RUNNING", "FINISHED")
        assert any("trickle" in v["name"] for v in detail["vertices"])
        assert detail["checkpoints"]["completed"] >= 1
        assert detail["checkpoints"]["recent"], detail["checkpoints"]
        assert "backpressure" in detail
        client.wait(30.0)
    finally:
        monitor.stop()


def test_monitor_serves_job_exceptions():
    """/jobs/<name>/exceptions: last failure cause, per-attempt
    history, restart count (ref: JobExceptionsHandler)."""
    from flink_tpu.core.functions import MapFunction

    class FailTwice(MapFunction):
        def __init__(self):
            self.failures = 0

        def map(self, value):
            if value == 5 and self.failures < 2:
                self.failures += 1
                raise RuntimeError(f"induced #{self.failures}")
            return value

    env = StreamExecutionEnvironment()
    env.set_restart_strategy("fixed_delay", restart_attempts=3,
                             delay_ms=0)
    sink = CollectSink()
    (env.from_collection(list(range(10)))
        .map(FailTwice())
        .add_sink(sink))
    client = env.execute_async("failing-job")
    result = client.wait(timeout=30)
    assert result.restarts == 2

    monitor = WebMonitor(env.get_metric_registry()).start()
    try:
        monitor.track_job("failing-job", client)
        exc, _ = _get(monitor.port, "/jobs/failing-job/exceptions")
        assert exc["restarts"] == 2
        assert len(exc["history"]) == 2
        assert "induced #2" in exc["last_failure"]
        assert [h["attempt"] for h in exc["history"]] == [0, 1]
        assert all("timestamp" in h and "exception" in h
                   for h in exc["history"])
    finally:
        monitor.stop()
