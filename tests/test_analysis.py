"""Pre-flight static analysis (flink_tpu/analysis/): graph linter,
UDF liftability, validate()/execute() wiring, CLI, metrics.

The differential contract between the liftability analyzer and the
runtime lift probe lives in tests/test_generic_agg.py; this file
covers the linter's code catalog on deliberately broken jobs and the
surfaces the analysis ships through.
"""

import json
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from flink_tpu.analysis import (
    CODES,
    Diagnostics,
    JobValidationError,
    analyze_aggregate,
    analyze_udf,
    lint_graph,
)
from flink_tpu.core.config import Configuration
from flink_tpu.core.functions import AggregateFunction
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.sources import CollectSink
from flink_tpu.streaming.windowing import (
    DeltaTrigger,
    EventTimeSessionWindows,
    TumblingEventTimeWindows,
)


# ---------------------------------------------------------------------
# liftability analyzer units
# ---------------------------------------------------------------------

_COUNTER = 0


def test_udf_global_write_is_impure():
    def f(x):
        global _COUNTER
        _COUNTER += 1
        return x

    assert analyze_udf(f).verdict == "IMPURE"


def test_udf_nonlocal_write_is_impure():
    state = [0]

    def make():
        total = 0.0

        def f(x):
            nonlocal total
            total += x
            return total
        return f

    assert analyze_udf(make()).verdict == "IMPURE"
    assert state  # silence the linter's own unused check


def test_udf_print_and_random_are_impure():
    import random
    assert analyze_udf(lambda x: print(x)).verdict == "IMPURE"
    assert analyze_udf(lambda x: x + random.random()).verdict == "IMPURE"


def test_udf_local_capture_is_not_impure():
    """A local variable captured by an inner lambda compiles to
    STORE_DEREF too — must not be mistaken for a nonlocal write."""
    def f(x):
        y = x + 1
        g = lambda: y   # noqa: E731 — forces y into a cell
        return g()

    assert analyze_udf(f).verdict != "IMPURE"


def test_udf_branch_is_scalar_only():
    rep = analyze_udf(lambda x: 1.0 if x > 0 else -1.0)
    assert rep.verdict == "SCALAR_ONLY"
    assert any("branch" in r for r in rep.reasons)


def test_udf_untainted_branch_is_inconclusive():
    """Branching on non-element state (a captured config flag) cannot
    conclusively prove scalar-only behaviour."""
    flag = True
    rep = analyze_udf(lambda x: x + 1 if flag else x - 1)
    assert rep.verdict == "INCONCLUSIVE"


def test_udf_unknown_helper_is_inconclusive():
    def helper(a):
        return a

    class Opaque:
        def __call__(self, a):
            return a

    opaque = Opaque()
    # helper recursion depth covers plain functions; an opaque
    # callable instance stays unknown
    assert analyze_udf(lambda x: opaque(x)).verdict == "INCONCLUSIVE"


def test_udf_ufunc_chain_is_liftable():
    rep = analyze_udf(lambda x: np.maximum(np.sqrt(x), 0.0) * 2 + 1)
    assert rep.verdict == "LIFTABLE"


def test_udf_loop_is_inconclusive():
    def f(xs):
        total = 0.0
        for x in xs:
            total += x
        return total

    assert analyze_udf(f).verdict == "INCONCLUSIVE"


def test_impure_aggregate_report():
    class Logging(AggregateFunction):
        def create_accumulator(self):
            return 0.0

        def add(self, v, acc):
            print("v", v)
            return acc + v

        def get_result(self, acc):
            return acc

        def merge(self, a, b):
            return a + b

    rep = analyze_aggregate(Logging())
    assert rep.verdict == "IMPURE"
    assert any("print" in r for r in rep.reasons)


def test_self_mutating_aggregate_is_impure():
    class Stateful(AggregateFunction):
        def __init__(self):
            self.seen = 0

        def create_accumulator(self):
            return 0.0

        def add(self, v, acc):
            self.seen += 1
            return acc + v

        def get_result(self, acc):
            return acc

        def merge(self, a, b):
            return a + b

    assert analyze_aggregate(Stateful()).verdict == "IMPURE"


# ---------------------------------------------------------------------
# graph linter on deliberately broken jobs
# ---------------------------------------------------------------------

def _base_env():
    env = StreamExecutionEnvironment()
    return env


def _codes(env):
    return env.validate().codes()


def test_clean_job_is_clean():
    env = _base_env()
    env.from_collection([1, 2, 3]).map(lambda x: x + 1) \
       .add_sink(CollectSink())
    report = env.validate()
    assert not report.has_errors()
    assert report.codes() == []


def test_unhashable_key_ft101():
    env = _base_env()
    (env.from_collection([(1, 2.0)], timestamped=False)
        .key_by(lambda x: [x[0]])
        .reduce(lambda a, b: a)
        .add_sink(CollectSink()))
    report = env.validate()
    assert "FT101" in report.codes()
    assert report.has_errors()


def test_trigger_assigner_rejection_ft110():
    env = _base_env()
    (env.from_collection([((1, 1.0), 10)], timestamped=True)
        .key_by(lambda x: x[0])
        .window(EventTimeSessionWindows.with_gap(100))
        .trigger(DeltaTrigger(1.0, lambda a, b: abs(a[1] - b[1])))
        .disable_device_operator()
        .reduce(lambda a, b: a)
        .add_sink(CollectSink()))
    report = env.validate()
    assert "FT110" in report.codes()


def test_session_gap_zero_ft111():
    env = _base_env()
    (env.from_collection([((1, 1.0), 10)], timestamped=True)
        .key_by(lambda x: x[0])
        .window(EventTimeSessionWindows.with_gap(0))
        .reduce(lambda a, b: a)
        .add_sink(CollectSink()))
    assert "FT111" in _codes(env)


def test_lateness_exceeds_window_ft112():
    env = _base_env()
    (env.from_collection([((1, 1.0), 10)], timestamped=True)
        .key_by(lambda x: x[0])
        .window(TumblingEventTimeWindows.of(10))
        .allowed_lateness(50)
        .reduce(lambda a, b: a)
        .add_sink(CollectSink()))
    report = env.validate()
    assert "FT112" in report.codes()
    assert not report.has_errors()   # a warning, not an error


def test_missing_timestamps_ft115():
    env = _base_env()
    (env.from_collection([(1, 1.0)], timestamped=False)
        .key_by(lambda x: x[0])
        .window(TumblingEventTimeWindows.of(10))
        .reduce(lambda a, b: a)
        .add_sink(CollectSink()))
    assert "FT115" in _codes(env)


def test_sinkless_and_unreachable_ft150_ft151():
    env = _base_env()
    env.from_collection([1, 2]).map(lambda x: x + 1)   # no sink
    report = env.validate()
    assert "FT150" in report.codes()

    # manually planted island: unreachable from any source
    from flink_tpu.streaming.graph import StreamNode
    from flink_tpu.streaming.operators import StreamMap
    from flink_tpu.core.functions import as_map_function
    g = env.graph
    nid = g.new_node_id()
    g.add_node(StreamNode(
        nid, "island",
        lambda: StreamMap(as_map_function(lambda x: x))))
    assert "FT151" in _codes(env)


def test_cycle_outside_iteration_ft160():
    env = _base_env()
    ds = env.from_collection([1, 2]).map(lambda x: x + 1)
    tail = ds.map(lambda x: x * 2)
    tail.add_sink(CollectSink())
    # hand-wire a feedback edge WITHOUT declaring an iteration
    from flink_tpu.streaming.graph import StreamEdge
    from flink_tpu.streaming.partitioners import ForwardPartitioner
    env.graph.add_edge(StreamEdge(tail.node.id, ds.node.id,
                                  ForwardPartitioner()))
    report = env.validate()
    assert "FT160" in report.codes()
    assert report.has_errors()


def test_declared_iteration_is_not_a_cycle():
    env = _base_env()
    it = env.from_collection([1, 2, 3]).iterate()
    body = it.map(lambda x: x - 1)
    out = it.close_with(body.filter(lambda x: x > 0))
    out.add_sink(CollectSink())
    report = env.validate()
    assert "FT160" not in report.codes()


def test_duplicate_uid_ft170_and_names_ft171():
    env = _base_env()
    a = env.from_collection([1]).map(lambda x: x).uid("same")
    a.map(lambda x: x).uid("same").add_sink(CollectSink())
    report = env.validate()
    assert "FT170" in report.codes()
    assert "FT171" in report.codes()   # both default to name "map"


def test_chaining_rejection_ft130_and_forward_mismatch_ft131():
    from flink_tpu.streaming.graph import chain_rejection_reasons
    env = _base_env()
    ds = env.from_collection([1, 2]).map(lambda x: x + 1)
    ds.add_sink(CollectSink())
    # head-only chaining downstream → FT130 with the reason string
    ds.node.chaining_strategy = "never"
    report = env.validate()
    ft130 = report.by_code("FT130")
    assert ft130 and "chaining strategy" in ft130[0].message

    # forward across a parallelism change → FT131 error
    env2 = _base_env()
    d2 = env2.from_collection([1, 2]).map(lambda x: x + 1)
    d2.node.parallelism = 4
    d2.add_sink(CollectSink())
    report2 = env2.validate()
    assert "FT131" in report2.codes()
    assert report2.has_errors()


def test_impure_aggregate_ft180_and_impure_map_ft183():
    class Timestamping(AggregateFunction):
        def create_accumulator(self):
            return 0.0

        def add(self, v, acc):
            import time
            return acc + v + 0 * time.time()

        def get_result(self, acc):
            return acc

        def merge(self, a, b):
            return a + b

    env = _base_env()
    (env.from_collection([((1, 1.0), 10)], timestamped=True)
        .key_by(lambda x: x[0])
        .window(TumblingEventTimeWindows.of(100))
        .aggregate(Timestamping())
        .add_sink(CollectSink()))
    env.from_collection([1]).map(lambda x: print(x)) \
       .add_sink(CollectSink())
    report = env.validate()
    assert "FT180" in report.codes()
    assert "FT183" in report.codes()
    assert report.has_errors()


def test_liftable_aggregate_ft182_info():
    class Summing(AggregateFunction):
        def create_accumulator(self):
            return 0.0

        def add(self, v, acc):
            return acc + v[1]

        def get_result(self, acc):
            return acc

        def merge(self, a, b):
            return a + b

    env = _base_env()
    (env.from_collection([((1, 1.0), 10)], timestamped=True)
        .key_by(lambda x: x[0])
        .window(TumblingEventTimeWindows.of(100))
        .aggregate(Summing())
        .add_sink(CollectSink()))
    report = env.validate()
    assert "FT182" in report.codes()
    assert not report.has_errors()


def test_every_emitted_code_is_catalogued():
    """The linter may only emit codes from the documented catalog."""
    env = _base_env()
    env.from_collection([1]).map(lambda x: print(x))
    for d in env.validate():
        assert d.code in CODES


# ---------------------------------------------------------------------
# validate()/execute() wiring
# ---------------------------------------------------------------------

def test_strict_mode_raises_and_warn_mode_executes():
    def broken(conf=None):
        env = StreamExecutionEnvironment(conf)
        sink = CollectSink()
        (env.from_collection([(1, 2.0)])
            .key_by(lambda x: [x[0]])
            .reduce(lambda a, b: a)
            .add_sink(sink))
        return env, sink

    conf = Configuration()
    conf.set("lint.mode", "strict")
    env, _ = broken(conf)
    with pytest.raises(JobValidationError) as ei:
        env.execute("strict-job")
    assert any(d.code == "FT101" for d in ei.value.report.errors())

    # warn (default): diagnostics logged, job still runs (and fails at
    # runtime for its own reasons or not — this one survives because
    # the scalar path hashes per-record and a 1-element list key is
    # only rejected when hashed; assert the report was captured)
    env2, _ = broken()
    try:
        env2.execute("warn-job")
    except Exception:
        pass  # runtime may legitimately reject the unhashable key
    assert env2._last_validation is not None
    assert "FT101" in env2._last_validation.codes()

    # off: no validation at all
    conf3 = Configuration()
    conf3.set("lint.mode", "off")
    env3 = StreamExecutionEnvironment(conf3)
    sink3 = CollectSink()
    env3.from_collection([1, 2]).map(lambda x: x + 1).add_sink(sink3)
    env3.execute("off-job")
    assert env3._last_validation is None
    assert sorted(sink3.values) == [2, 3]


def test_lint_metrics_registered():
    env = StreamExecutionEnvironment()
    sink = CollectSink()
    (env.from_collection([(1, 2.0)], timestamped=False)
        .key_by(lambda x: x[0])
        .reduce(lambda a, b: (a[0], a[1] + b[1]))
        .add_sink(sink))
    env.execute("lint-metrics-job")
    reg = env.get_metric_registry()
    snap = reg.snapshot() if hasattr(reg, "snapshot") else reg.dump()
    lint = {k: v for k, v in snap.items() if ".lint." in str(k)}
    assert lint.get("lint-metrics-job.lint.errors") == 0
    # the keyed reduce on a bounded source emits FT140 at INFO
    assert lint.get("lint-metrics-job.lint.infos", 0) >= 1
    assert any(".lint.codes.FT140" in str(k) for k in snap)


# ---------------------------------------------------------------------
# script lint + CLI
# ---------------------------------------------------------------------

_GOOD_SCRIPT = textwrap.dedent("""
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment
    from flink_tpu.streaming.sources import CollectSink

    env = StreamExecutionEnvironment()
    sink = CollectSink()
    env.from_collection([1, 2, 3]).map(lambda x: x * 2).add_sink(sink)
    env.execute("good-job")
    assert sink.values == []   # lint mode: nothing actually ran
""")

_BAD_SCRIPT = textwrap.dedent("""
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment
    from flink_tpu.streaming.sources import CollectSink

    env = StreamExecutionEnvironment()
    (env.from_collection([(1, 2.0)])
        .key_by(lambda x: [x[0]])
        .reduce(lambda a, b: a)
        .add_sink(CollectSink()))
    env.execute("bad-job")
""")


def test_lint_script_captures_without_running(tmp_path):
    from flink_tpu.analysis.script_lint import lint_script
    p = tmp_path / "good_job.py"
    p.write_text(_GOOD_SCRIPT)
    res = lint_script(str(p))
    assert res.script_error is None
    assert [name for name, _ in res.reports] == ["good-job"]
    assert not res.has_errors()


def test_lint_script_surfaces_errors(tmp_path):
    from flink_tpu.analysis.script_lint import lint_script
    p = tmp_path / "bad_job.py"
    p.write_text(_BAD_SCRIPT)
    res = lint_script(str(p))
    assert res.has_errors()
    assert "FT101" in res.reports[0][1].codes()


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "flink_tpu", "lint", *args],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "."})


@pytest.mark.slow
def test_cli_lint_exit_codes(tmp_path):
    good = tmp_path / "good_job.py"
    good.write_text(_GOOD_SCRIPT)
    bad = tmp_path / "bad_job.py"
    bad.write_text(_BAD_SCRIPT)

    r = _run_cli(str(good))
    assert r.returncode == 0, r.stderr
    assert "0 error(s)" in r.stdout

    r = _run_cli("--json", str(bad))
    assert r.returncode == 1
    payload = json.loads(r.stdout[r.stdout.index("["):])
    diag_codes = [d["code"]
                  for entry in payload for job in entry["jobs"]
                  for d in job["diagnostics"]]
    assert "FT101" in diag_codes

    r = _run_cli()
    assert r.returncode == 2


# ---------------------------------------------------------------------
# unused-import checker
# ---------------------------------------------------------------------

def test_imports_check_flags_only_unused(tmp_path):
    from flink_tpu.analysis.imports_check import check_file
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent("""
        import os
        import sys
        import json  # noqa
        from typing import List, Optional

        def f(paths: List[str]):
            return [sys.intern(p) for p in paths]
    """))
    found = {f.name for f in check_file(str(p))}
    assert found == {"os", "Optional"}   # sys/List used, json noqa'd


def test_imports_check_respects_init_reexports(tmp_path):
    from flink_tpu.analysis.imports_check import check_file
    p = tmp_path / "__init__.py"
    p.write_text("from .mod import thing\n")
    assert check_file(str(p)) == []


def test_repo_has_no_unused_imports():
    from flink_tpu.analysis.imports_check import check_tree
    findings = check_tree("flink_tpu")
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------
# diagnostics plumbing
# ---------------------------------------------------------------------

def test_diagnostics_ordering_and_counts():
    r = Diagnostics(job_name="j")
    r.add("FT130", "info thing")
    r.add("FT101", "error thing")
    r.add("FT112", "warning thing")
    assert [d.code for d in r] == ["FT101", "FT112", "FT130"]
    assert r.counts() == {"error": 1, "warning": 1, "info": 1}
    assert r.has_errors()
    txt = r.render()
    assert "1 error(s)" in txt and "FT101" in txt
    d = r.to_dict()
    assert d["counts"]["error"] == 1
    assert len(d["diagnostics"]) == 3


def test_diagnostic_severity_defaults_from_catalog():
    r = Diagnostics()
    assert r.add("FT101", "m").severity == "error"
    assert r.add("FT112", "m").severity == "warning"
    assert r.add("FT130", "m").severity == "info"
    # explicit override wins
    assert r.add("FT140", "m", severity="info").severity == "info"
