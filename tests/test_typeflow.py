"""Whole-graph column type-flow prover (flink_tpu/analysis/typeflow):
schema inference, the dtype abstract interpreter, FT185-FT188 seeding,
and the differential contract against the runtime first-batch probe —
the prover must never issue a conclusive verdict the runtime
contradicts, and statically proven chains must run with ZERO probes
and byte-identical output."""

import json
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from flink_tpu.analysis.typeflow import (
    analyze_graph,
    apply_static,
    codec_tier,
)
from flink_tpu.core.config import (
    Configuration,
    LINT_MODES,
    LintOptions,
    lint_mode_of,
)
from flink_tpu.streaming import operators as op_mod
from flink_tpu.streaming.columnar import (
    VectorizedCollectionSource,
    batch_from_records,
)
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.elements import StreamRecord
from flink_tpu.streaming.sources import CollectSink
from flink_tpu.streaming.windowing import Time
from flink_tpu.ops.device_agg import SumAggregate


def _env(conf=None):
    return StreamExecutionEnvironment(conf)


def _analyze(env):
    return analyze_graph(env.graph, config=env.config)


def _node_id(env, name):
    ids = [nid for nid, n in env.graph.nodes.items() if n.name == name]
    assert ids, f"no node named {name}"
    return ids[0]


class TupleSum(SumAggregate):
    def __init__(self):
        super().__init__(np.float32)

    def extract_value(self, value):
        return value[1]


# ---------------------------------------------------------------------
# source schema inference
# ---------------------------------------------------------------------

def test_vectorized_source_schema_is_exact():
    env = _env()
    env.add_source(VectorizedCollectionSource([3, 1, 7])) \
       .add_sink(CollectSink())
    tf = _analyze(env)
    schema = tf.node_schemas[_node_id(env, "source")]
    assert schema.conclusive and schema.scalar
    (c,) = schema.cols
    assert c.token == "i8" and (c.lo, c.hi) == (1.0, 7.0)


def test_from_collection_schemas():
    env = _env()
    env.from_collection([0.5, 1.5]).add_sink(CollectSink())
    env.from_collection(["a", "bb"]).add_sink(CollectSink())
    env.from_collection([(1, 2.0), (3, 4.0)]).add_sink(CollectSink())
    tf = _analyze(env)
    by_name = {env.graph.nodes[nid].name: s
               for nid, s in tf.node_schemas.items()}
    srcs = [s for nid, s in tf.node_schemas.items()
            if env.graph.nodes[nid].name == "from_collection"]
    tokens = sorted(s.tokens() for s in srcs)
    assert tokens == [("f8",), ("i8", "f8"), ("str",)]
    assert all(s.conclusive for s in srcs)
    assert by_name  # schemas exist for every node


def test_unbounded_source_is_inconclusive():
    env = _env()
    env.socket_text_stream("localhost", 9999).add_sink(CollectSink())
    tf = _analyze(env)
    schema = tf.node_schemas[_node_id(env, "socket_source")]
    assert not schema.conclusive


def test_codec_tier_vocabulary():
    env = _env()
    env.add_source(VectorizedCollectionSource([1, 2])) \
       .map(lambda x: np.float32(x)).add_sink(CollectSink())
    tf = _analyze(env)
    schema = tf.node_schemas[_node_id(env, "map")]
    assert schema.conclusive and schema.tokens() == ("f4",)
    tier, blocker = codec_tier(schema)
    assert (tier, blocker) == ("pickle", "f4")
    src = tf.node_schemas[_node_id(env, "source")]
    assert codec_tier(src) == ("col", "")


# ---------------------------------------------------------------------
# kernel dtype inference
# ---------------------------------------------------------------------

def _kernel_of(fn, values, op="map"):
    env = _env()
    ds = env.add_source(VectorizedCollectionSource(list(values)))
    ds = ds.map(fn) if op == "map" else ds.filter(fn)
    ds.add_sink(CollectSink())
    tf = _analyze(env)
    return tf.kernels[_node_id(env, op)]


def test_int_arithmetic_stays_i8():
    v = _kernel_of(lambda x: x * 2 + 1, range(100))
    assert v.proven and v.out_schema.tokens() == ("i8",)
    (c,) = v.out_schema.cols
    assert (c.lo, c.hi) == (1.0, 199.0)


def test_truediv_promotes_to_f8():
    v = _kernel_of(lambda x: x / 2, range(10))
    assert v.proven and v.out_schema.tokens() == ("f8",)


def test_tuple_output_schema():
    v = _kernel_of(lambda x: (x, x + 0.5), range(10))
    assert v.proven
    assert v.out_schema.tokens() == ("i8", "f8")
    assert not v.out_schema.scalar


def test_float32_preserved_through_ufunc():
    v = _kernel_of(lambda x: np.sqrt(np.float32(x)) * 2, range(10))
    assert v.proven and v.out_schema.tokens() == ("f4",)


def test_filter_predicate_proves_bool():
    v = _kernel_of(lambda x: x > 10, range(100), op="filter")
    assert v.proven
    # filters never change values: out schema is the in schema
    assert v.out_schema.tokens() == ("i8",)


def test_branchy_udf_is_not_proven():
    v = _kernel_of(lambda x: x * 2 if x % 2 else x - 1, range(10))
    assert not v.proven


def test_opaque_call_is_not_proven():
    d = {"k": 1}
    v = _kernel_of(lambda x: d.get("k", x), range(10))
    assert not v.proven


def test_tuple_field_access():
    env = _env()
    vals = [(i, float(i) * 0.5) for i in range(20)]
    env.add_source(VectorizedCollectionSource(vals)) \
       .map(lambda t: t[1] * 2).add_sink(CollectSink())
    tf = _analyze(env)
    v = tf.kernels[_node_id(env, "map")]
    assert v.proven and v.out_schema.tokens() == ("f8",)


def test_inconclusive_input_blocks_kernel_proof():
    env = _env()
    env.socket_text_stream("localhost", 9999) \
       .map(lambda x: x).add_sink(CollectSink())
    tf = _analyze(env)
    v = tf.kernels[_node_id(env, "map")]
    assert not v.proven and "inconclusive" in v.note


# ---------------------------------------------------------------------
# soundness differential: prover vs first-batch probe (the zoo)
# ---------------------------------------------------------------------

# (fn, values) spanning proven kernels, probe-demoted kernels, and
# raise-demoted kernels.  The contract under test: the prover NEVER
# proves a kernel the runtime probe would demote.
_ZOO = [
    (lambda v: v * 3 + 1, list(range(50))),
    (lambda v: v / 4, list(range(50))),
    (lambda v: (v, v * 2.0), list(range(30))),
    (lambda t: (t[0], t[1] * 2.0), [(i, float(i)) for i in range(30)]),
    # data-dependent branch: probe never runs (liftability demotes)
    (lambda v: v * 2 if v % 2 else v - 1, list(range(40))),
    # int64 wraparound the probe catches: interval escapes int64
    (lambda v: v << 70, list(range(1, 20))),
    # kernel raises on arrays (array index into a constant tuple)
    (lambda v: (10, 20, 30)[v], [i % 3 for i in range(30)]),
]


def _probe_decision(fn, values):
    """Run the real operator machinery on one batch; returns
    (demoted, rows) with rows the flattened output."""
    from flink_tpu.core.functions import _LambdaMap
    from flink_tpu.streaming.operators import StreamMap

    class _Cap:
        def __init__(self):
            self.elements = []

        def collect(self, r):
            self.elements.append((r.value, r.timestamp))

        def collect_batch(self, b):
            self.elements.extend(zip(b.row_values(), b.timestamps()))

        def emit_watermark(self, w):
            pass

    op = StreamMap(_LambdaMap(fn))
    out = _Cap()
    op.setup(out)
    op.open()
    op.process_batch(batch_from_records(list(values),
                                        list(range(len(values)))))
    return op._batch_kernel is False, out.elements


@pytest.mark.parametrize("idx", range(len(_ZOO)))
def test_prover_never_eligible_where_probe_demotes(idx):
    fn, values = _ZOO[idx]
    verdict = _kernel_of(fn, values)
    demoted, rows = _probe_decision(fn, values)
    if demoted:
        assert not verdict.proven, (
            f"prover claimed a kernel the probe demotes: {verdict}")
    # either way the operator output matches the scalar ground truth
    want = [(fn(v), t) for t, v in enumerate(values)]
    assert rows == want


def test_proven_kernel_output_matches_boxed_path():
    """Byte-identical results: statically proven kernel vs the
    per-record boxed execution of the same UDF."""
    from flink_tpu.core.functions import _LambdaMap
    from flink_tpu.streaming.operators import StreamMap
    for fn, values in _ZOO[:4]:
        verdict = _kernel_of(fn, values)
        assert verdict.proven

        class _Cap:
            def __init__(self):
                self.rows = []

            def collect(self, r):
                self.rows.append((r.value, r.timestamp))

            def collect_batch(self, b):
                self.rows.extend(zip(b.row_values(), b.timestamps()))

            def emit_watermark(self, w):
                pass

        ts = list(range(len(values)))
        op = StreamMap(_LambdaMap(fn))
        op._static_kernel = True        # what apply_static stamps
        cap = _Cap()
        op.setup(cap)
        op.open()
        op.process_batch(batch_from_records(list(values), ts))
        assert op.columnar_decided_by == "static"
        assert op.kernel_probes == 0
        boxed_op = StreamMap(_LambdaMap(fn))
        boxed = _Cap()
        boxed_op.setup(boxed)
        boxed_op.open()
        for v, t in zip(values, ts):
            boxed_op.process_element(StreamRecord(v, t))
        assert cap.rows == boxed.rows


def test_static_stamp_still_demotes_on_runtime_mismatch():
    """The emit-side shape validation stays armed for statically
    stamped kernels: a wrong stamp demotes boxed, never corrupts."""
    from flink_tpu.core.functions import _LambdaMap
    from flink_tpu.streaming.operators import StreamMap

    class _Cap:
        def __init__(self):
            self.rows = []

        def collect(self, r):
            self.rows.append((r.value, r.timestamp))

        def collect_batch(self, b):
            self.rows.extend(zip(b.row_values(), b.timestamps()))

        def emit_watermark(self, w):
            pass

    fn = lambda v: {"k": v}  # noqa: E731 — not a column shape
    op = StreamMap(_LambdaMap(fn))
    op._static_kernel = True  # deliberately wrong stamp
    cap = _Cap()
    op.setup(cap)
    op.open()
    values, ts = list(range(5)), list(range(5))
    op.process_batch(batch_from_records(values, ts))
    assert op._batch_kernel is False
    assert op.columnar_decided_by is None
    assert cap.rows == [({"k": v}, t) for v, t in zip(values, ts)]


# ---------------------------------------------------------------------
# probe-free end-to-end execution
# ---------------------------------------------------------------------

def _chain_env(types_mode):
    conf = Configuration()
    if types_mode:
        conf.set("lint.types.mode", types_mode)
    env = _env(conf)
    env.set_parallelism(1)
    sink = CollectSink()
    env.add_source(VectorizedCollectionSource(list(range(1, 101)))) \
       .map(lambda x: x * 2).filter(lambda x: x > 10) \
       .map(lambda x: (x, x + 0.5)).add_sink(sink)
    return env, sink


def test_statically_proven_chain_runs_probe_free():
    op_mod.KERNEL_STATS.reset()
    env, sink = _chain_env("warn")
    env.execute("typeflow-static")
    static_out = list(sink.values)
    assert op_mod.KERNEL_STATS.probes == 0
    assert op_mod.KERNEL_STATS.static_skips >= 3

    op_mod.KERNEL_STATS.reset()
    env2, sink2 = _chain_env(None)
    env2.execute("typeflow-probed")
    assert op_mod.KERNEL_STATS.probes >= 3
    assert op_mod.KERNEL_STATS.static_skips == 0
    assert static_out == list(sink2.values)


def test_apply_static_counts_and_idempotence():
    env, _ = _chain_env(None)
    tf = _analyze(env)
    applied = apply_static(env.graph, tf)
    assert applied["kernels_proven"] == 3
    # re-applying replaces the factory wrap instead of stacking
    applied2 = apply_static(env.graph, tf)
    assert applied2 == applied
    for node in env.graph.nodes.values():
        f = node.operator_factory
        orig = getattr(f, "_typeflow_orig", None)
        if orig is not None:
            assert not hasattr(orig, "_typeflow_orig")


def test_decided_by_surfaces():
    from flink_tpu.analysis.columnar_eligibility import (
        chain_report,
        operator_decided_by,
    )
    env, _ = _chain_env(None)
    tf = _analyze(env)
    apply_static(env.graph, tf)
    ops = [n.operator_factory() for n in env.graph.nodes.values()]
    decided = [operator_decided_by(op) for op in ops]
    assert decided.count("static") == 3
    rep = chain_report(ops)
    assert len(rep["decided_by"]) == len(rep["modes"])
    assert rep["decided_by"].count("static") == 3


# ---------------------------------------------------------------------
# seeded FT185-FT188
# ---------------------------------------------------------------------

def test_ft185_pickle_tier_exchange_edge():
    env = _env()
    env.add_source(VectorizedCollectionSource([1, 2, 3, 4])) \
       .map(lambda x: x > 2).rebalance().add_sink(CollectSink())
    tf = _analyze(env)
    (d,) = tf.diagnostics.by_code("FT185")
    assert d.severity == "warning"
    assert "bool" in d.message and "map" in d.message
    # forward edges with the same schema do NOT fire
    env2 = _env()
    env2.add_source(VectorizedCollectionSource([1, 2, 3, 4])) \
        .map(lambda x: x > 2).add_sink(CollectSink())
    assert not _analyze(env2).diagnostics.by_code("FT185")


def test_ft186_int64_overflow_hazard():
    env = _env()
    vals = list(range(2 ** 29, 2 ** 30, 2 ** 20))
    env.add_source(VectorizedCollectionSource(vals)) \
       .map(lambda x: x << 40).add_sink(CollectSink())
    tf = _analyze(env)
    (d,) = tf.diagnostics.by_code("FT186")
    assert d.severity == "warning"
    # the hazardous kernel keeps its probe: NOT proven
    assert not tf.kernels[_node_id(env, "map")].proven
    # same shift on values that cannot escape int64: no hazard
    env2 = _env()
    env2.add_source(VectorizedCollectionSource([1, 2, 3])) \
        .map(lambda x: x << 40).add_sink(CollectSink())
    tf2 = _analyze(env2)
    assert not tf2.diagnostics.by_code("FT186")
    assert tf2.kernels[_node_id(env2, "map")].proven


def test_ft187_state_footprint_over_budget():
    conf = Configuration()
    conf.set("state.backend.tpu.max-device-slots", 16)
    env = _env(conf)
    recs = [((k, 1.0), k) for k in range(64)]
    (env.from_collection(recs, timestamped=True)
        .key_by(lambda t: t[0])
        .time_window(Time.seconds(1))
        .aggregate(TupleSum())
        .add_sink(CollectSink()))
    tf = _analyze(env)
    (d,) = tf.diagnostics.by_code("FT187")
    assert d.severity == "warning"
    assert "64" in d.message and "16" in d.message
    (fp,) = tf.footprints.values()
    assert fp.slots == 64 and fp.over_budget
    # within budget: estimate recorded, no finding
    conf2 = Configuration()
    conf2.set("state.backend.tpu.max-device-slots", 128)
    env2 = _env(conf2)
    (env2.from_collection(recs, timestamped=True)
         .key_by(lambda t: t[0])
         .time_window(Time.seconds(1))
         .aggregate(TupleSum())
         .add_sink(CollectSink()))
    tf2 = _analyze(env2)
    assert not tf2.diagnostics.by_code("FT187")
    (fp2,) = tf2.footprints.values()
    assert fp2.slots == 64 and not fp2.over_budget


def test_ft187_presizes_engine_capacity():
    env = _env()
    recs = [((k, 1.0), k) for k in range(300)]
    (env.from_collection(recs, timestamped=True)
        .key_by(lambda t: t[0])
        .time_window(Time.seconds(1))
        .aggregate(TupleSum())
        .add_sink(CollectSink()))
    tf = _analyze(env)
    apply_static(env.graph, tf)
    nid = _node_id(env, "window_aggregate")
    op = env.graph.nodes[nid].operator_factory()
    assert op._predicted_slots == 300
    assert op.initial_capacity >= 512  # next pow2 over 300


def test_ft188_union_schema_conflict():
    env = _env()
    a = env.add_source(VectorizedCollectionSource([1, 2, 3]))
    b = env.add_source(VectorizedCollectionSource(["a", "b"]))
    a.union(b).add_sink(CollectSink())
    tf = _analyze(env)
    (d,) = tf.diagnostics.by_code("FT188")
    assert d.severity == "warning"
    assert "i8" in d.message and "str" in d.message
    # agreeing branches merge cleanly with unioned bounds
    env2 = _env()
    a2 = env2.add_source(VectorizedCollectionSource([1, 2]))
    b2 = env2.add_source(VectorizedCollectionSource([10, 20]))
    u = a2.union(b2)
    u.add_sink(CollectSink())
    tf2 = _analyze(env2)
    assert not tf2.diagnostics.by_code("FT188")
    schema = tf2.node_schemas[u.node.id]
    assert schema.conclusive
    (c,) = schema.cols
    assert (c.lo, c.hi) == (1.0, 20.0)


def test_every_typeflow_code_is_catalogued():
    from flink_tpu.analysis import CODES
    for code in ("FT185", "FT186", "FT187", "FT188"):
        assert code in CODES
        assert CODES[code][0] == "warning"


# ---------------------------------------------------------------------
# netchannel codec hint
# ---------------------------------------------------------------------

def test_encode_hint_skips_columnar_attempt():
    from flink_tpu.runtime import netchannel
    records = [StreamRecord({"k": i}, i) for i in range(4)]
    netchannel.NET_STATS.reset()
    organic = netchannel.encode_elements(list(records))
    hinted = netchannel.encode_elements(list(records), hint="pickle")
    assert hinted[0] == "pickle" and organic[0] == "pickle"
    decoded_h = netchannel.decode_elements(hinted)
    decoded_o = netchannel.decode_elements(organic)
    assert [(r.value, r.timestamp) for r in decoded_h] == \
        [(r.value, r.timestamp) for r in decoded_o]
    assert netchannel.NET_STATS.predicted_skips == 1
    snap = netchannel.NET_STATS.snapshot()
    assert snap["predictedSkips"] == 1


def test_predicted_tier_table_only_keeps_known_tiers():
    from flink_tpu.runtime import netchannel
    netchannel.note_predicted_tier("j", 0, "pickle")
    assert netchannel.PREDICTED_TIERS[("j", 0)] == "pickle"
    netchannel.note_predicted_tier("j", 0, None)
    assert ("j", 0) not in netchannel.PREDICTED_TIERS
    netchannel.note_predicted_tier("j", 1, "weird")
    assert ("j", 1) not in netchannel.PREDICTED_TIERS


def test_predicted_tier_lands_on_job_edge():
    env = _env()
    env.add_source(VectorizedCollectionSource([1, 2, 3, 4])) \
       .map(lambda x: x > 2).rebalance().add_sink(CollectSink())
    tf = _analyze(env)
    applied = apply_static(env.graph, tf)
    assert applied["edges_predicted"] == 1
    jg = env.get_job_graph()
    tiers = [e.predicted_codec_tier for e in jg.edges]
    assert "pickle" in tiers


# ---------------------------------------------------------------------
# config gate + validate()/execute() wiring
# ---------------------------------------------------------------------

def test_lint_types_mode_accepted_names():
    conf = Configuration()
    assert lint_mode_of(conf, LintOptions.TYPES_MODE) == "off"
    assert lint_mode_of(conf, LintOptions.MODE) == "warn"
    for mode in LINT_MODES:
        conf.set("lint.types.mode", mode)
        assert lint_mode_of(conf, LintOptions.TYPES_MODE) == mode
    conf.set("lint.types.mode", "bogus")
    with pytest.raises(ValueError) as ei:
        lint_mode_of(conf, LintOptions.TYPES_MODE)
    assert "lint.types.mode" in str(ei.value)
    assert "off" in str(ei.value) and "strict" in str(ei.value)


def test_unknown_types_mode_fails_execute():
    conf = Configuration()
    conf.set("lint.types.mode", "aggressive")
    env = _env(conf)
    env.from_collection([1, 2]).map(lambda x: x).add_sink(CollectSink())
    with pytest.raises(ValueError):
        env.execute("bad-mode")


def test_types_strict_raises_on_seeded_finding():
    from flink_tpu.analysis import JobValidationError
    conf = Configuration()
    conf.set("lint.types.mode", "strict")
    env = _env(conf)
    env.add_source(VectorizedCollectionSource([1, 2, 3, 4])) \
       .map(lambda x: x > 2).rebalance().add_sink(CollectSink())
    with pytest.raises(JobValidationError) as ei:
        env.execute("strict-types")
    assert "FT185" in ei.value.report.codes()


def test_types_warn_executes_and_keeps_report():
    conf = Configuration()
    conf.set("lint.types.mode", "warn")
    env = _env(conf)
    sink = CollectSink()
    env.add_source(VectorizedCollectionSource([1, 2, 3, 4])) \
       .map(lambda x: x > 2).rebalance().add_sink(sink)
    env.execute("warn-types")
    assert sorted(sink.values) == [False, False, True, True]
    assert env._last_typeflow is not None
    assert "FT185" in env._last_validation.codes()


def test_config_docs_reflect_types_mode():
    from flink_tpu.core.config_docs import generate_config_docs
    md = generate_config_docs()
    assert "lint.types.mode" in md and "lint.mode" in md


def test_typeflow_metrics_registered():
    conf = Configuration()
    conf.set("lint.types.mode", "warn")
    env = _env(conf)
    sink = CollectSink()
    env.add_source(VectorizedCollectionSource(list(range(20)))) \
       .map(lambda x: x * 2).add_sink(sink)
    env.execute("tf-metrics")
    reg = env.get_metric_registry()
    snap = reg.snapshot() if hasattr(reg, "snapshot") else reg.dump()
    tf = {str(k): v for k, v in snap.items() if ".typeflow." in str(k)}
    assert tf.get("tf-metrics.typeflow.kernels_proven") == 1
    assert tf.get("tf-metrics.typeflow.edges_conclusive") == 2
    decided = {str(k): v for k, v in snap.items()
               if str(k).endswith(".columnar.decided_by")}
    assert "static" in decided.values()


# ---------------------------------------------------------------------
# linter integration: lint_graph(types=), FT184 enrichment, validate()
# ---------------------------------------------------------------------

def test_lint_graph_types_opt_in():
    from flink_tpu.analysis.graph_linter import lint_graph
    env = _env()
    env.add_source(VectorizedCollectionSource([1, 2, 3, 4])) \
       .map(lambda x: x > 2).rebalance().add_sink(CollectSink())
    plain = lint_graph(env.graph)
    assert "FT185" not in plain.codes()
    typed = lint_graph(env.graph, types=True)
    assert "FT185" in typed.codes()
    assert typed.typeflow is not None
    assert typed.typeflow.summary()["pickle_edges"] == 1


def test_ft184_names_the_boxing_edge_schema():
    from flink_tpu.analysis.graph_linter import lint_graph
    env = _env()
    (env.add_source(VectorizedCollectionSource(list(range(10))))
        .map(lambda v: v + 1)
        .map(lambda v: v * 2 if v else v)   # first blocker
        .add_sink(CollectSink()))
    report = lint_graph(env.graph, types=True)
    ft184 = [d for d in report.by_code("FT184")
             if "boxes at" in d.message]
    assert ft184
    assert any("boxing the edge" in d.message and "i8" in d.message
               for d in ft184)


def test_script_lint_types(tmp_path):
    from flink_tpu.analysis.script_lint import lint_script
    p = tmp_path / "pickle_edge_job.py"
    p.write_text(textwrap.dedent("""
        from flink_tpu.streaming.columnar import VectorizedCollectionSource
        from flink_tpu.streaming.datastream import StreamExecutionEnvironment
        from flink_tpu.streaming.sources import CollectSink

        env = StreamExecutionEnvironment()
        env.add_source(VectorizedCollectionSource([1, 2, 3, 4])) \\
           .map(lambda x: x > 2).rebalance().add_sink(CollectSink())
        env.execute("pickle-edge-job")
    """))
    res = lint_script(str(p), types=True)
    assert res.script_error is None
    (name, report) = res.reports[0]
    assert "FT185" in report.codes()
    assert report.typeflow is not None
    # without --types the same script is silent
    res2 = lint_script(str(p))
    assert "FT185" not in res2.reports[0][1].codes()


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "flink_tpu", "lint", *args],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "."})


@pytest.mark.slow
def test_cli_lint_types_strict_flags_seeds(tmp_path):
    p = tmp_path / "seeded_job.py"
    p.write_text(textwrap.dedent("""
        from flink_tpu.core.config import Configuration
        from flink_tpu.streaming.columnar import VectorizedCollectionSource
        from flink_tpu.streaming.datastream import StreamExecutionEnvironment
        from flink_tpu.streaming.sources import CollectSink
        from flink_tpu.streaming.windowing import Time
        import numpy as np
        from flink_tpu.ops.device_agg import SumAggregate

        class TupleSum(SumAggregate):
            def __init__(self):
                super().__init__(np.float32)
            def extract_value(self, value):
                return value[1]

        conf = Configuration()
        conf.set("state.backend.tpu.max-device-slots", 16)
        env = StreamExecutionEnvironment(conf)
        env.add_source(VectorizedCollectionSource([1, 2, 3, 4])) \\
           .map(lambda x: x > 2).rebalance().add_sink(CollectSink())
        recs = [((k, 1.0), k) for k in range(64)]
        (env.from_collection(recs, timestamped=True)
            .key_by(lambda t: t[0])
            .time_window(Time.seconds(1))
            .aggregate(TupleSum())
            .add_sink(CollectSink()))
        env.execute("seeded-job")
    """))
    r = _run_cli("--types", "--strict", "--json", str(p))
    assert r.returncode == 1, r.stderr
    payload = json.loads(r.stdout[r.stdout.index("["):])
    jobs = [j for entry in payload for j in entry["jobs"]]
    codes = [d["code"] for j in jobs for d in j["diagnostics"]]
    assert "FT185" in codes and "FT187" in codes
    tf = jobs[0].get("typeflow")
    assert tf and tf["summary"]["pickle_edges"] == 1
    assert any(e["codec_tier"] == "pickle" for e in tf["edges"])
    # the job never executed: lint captures, doesn't run
    assert "seeded-job" in r.stdout or jobs
