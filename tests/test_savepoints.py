"""Savepoints + rescale-on-restore (ref: SavepointITCase.java +
RescalingITCase.java — SURVEY.md §4.4): trigger a savepoint on a live
job, stop-with-savepoint, resume a NEW job from it at the same and at
a DIFFERENT parallelism, and verify exactly-once counts plus operator
list-state round-robin re-splitting."""

import os
import time

import pytest

from flink_tpu.core.functions import AggregateFunction, MapFunction
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.sources import CollectSink, FromCollectionSource
from flink_tpu.streaming.windowing import Time


class SumAgg(AggregateFunction):
    def create_accumulator(self):
        return 0.0

    def add(self, value, acc):
        return acc + value[1]

    def get_result(self, acc):
        return acc

    def merge(self, a, b):
        return a + b


def _records(n_keys=6, per_key=300):
    records = []
    for i in range(per_key):
        for k in range(n_keys):
            records.append(((f"k{k}", 1), i * 10))
    return records


class PausingSource(FromCollectionSource):
    """Emits the first `free` records, then idles until `release()`
    (class-level gate) — keeps the job alive while the test triggers a
    savepoint mid-stream."""

    released = False
    FREE = 600

    @classmethod
    def reset(cls):
        cls.released = False

    def emit_step(self, ctx, max_records):
        if not type(self).released and self.offset >= self.FREE:
            time.sleep(0.001)
            return True
        return super().emit_step(ctx, max_records)


def _build(env, records, sink, parallelism=1):
    env.set_parallelism(parallelism)
    (env.add_source(PausingSource(records, timestamped=True),
                    name="pausing")
        .key_by(lambda v: v[0])
        .time_window(Time.milliseconds_of(1000))
        .aggregate(SumAgg())
        .add_sink(sink))


@pytest.mark.parametrize("executor", ["local", "mini"])
def test_savepoint_and_resume_same_parallelism(tmp_path, executor):
    PausingSource.reset()
    records = _records()
    env = StreamExecutionEnvironment()
    if executor == "mini":
        env.use_mini_cluster(2)
    env.enable_checkpointing(10)
    _build(env, records, CollectSink())
    client = env.execute_async("savepoint-origin")
    path = client.trigger_savepoint(str(tmp_path / "sp"))
    assert os.path.exists(path)
    # stop the original job (savepoint already taken)
    client.cancel()
    client.wait(30.0)

    # resume a FRESH job from the savepoint: source offset rewinds to
    # the snapshot point, window state carries partial sums
    PausingSource.released = True
    sink2 = CollectSink()
    env2 = StreamExecutionEnvironment()
    if executor == "mini":
        env2.use_mini_cluster(2)
    env2.set_savepoint_restore(path)
    _build(env2, records, sink2)
    result = env2.execute("savepoint-resume")
    assert sum(sink2.values) == len(records)
    assert result.restarts == 0


def test_stop_with_savepoint_and_rescale(tmp_path):
    """Savepoint at parallelism 1, resume at parallelism 2 (and the
    reverse) — the RescalingITCase shape through the full executor."""
    PausingSource.reset()
    records = _records()
    env = StreamExecutionEnvironment()
    env.enable_checkpointing(10)
    _build(env, records, CollectSink(), parallelism=1)
    client = env.execute_async("rescale-origin")
    path = client.stop_with_savepoint(str(tmp_path / "sp"))
    assert os.path.exists(path)

    PausingSource.released = True
    sink2 = CollectSink()
    env2 = StreamExecutionEnvironment()
    env2.set_savepoint_restore(path)
    _build(env2, records, sink2, parallelism=2)  # SCALE UP
    env2.execute("rescale-up")
    assert sum(sink2.values) == len(records)

    # scale back DOWN from a parallelism-2 savepoint
    PausingSource.reset()
    env3 = StreamExecutionEnvironment()
    env3.enable_checkpointing(10)
    sink3 = CollectSink()
    _build(env3, records, sink3, parallelism=2)
    client3 = env3.execute_async("rescale-origin-2")
    path2 = client3.stop_with_savepoint(str(tmp_path / "sp2"))

    PausingSource.released = True
    sink4 = CollectSink()
    env4 = StreamExecutionEnvironment()
    env4.set_savepoint_restore(path2)
    _build(env4, records, sink4, parallelism=1)  # SCALE DOWN
    env4.execute("rescale-down")
    assert sum(sink4.values) == len(records)


class ListStateMap(MapFunction):
    """Carries per-subtask operator list state (the Kafka-offset
    shape) — used to verify round-robin re-splitting on rescale."""

    def __init__(self):
        self.items = []

    def open(self, configuration=None):
        pass

    def snapshot_function_state(self, checkpoint_id=None):
        return {"items": list(self.items)}

    def restore_function_state(self, state):
        self.items = list(state["items"])

    def map(self, value):
        return value


def test_savepoint_requires_checkpointing():
    PausingSource.reset()  # gated: the job stays alive for the call
    env = StreamExecutionEnvironment()
    _build(env, _records(per_key=200), CollectSink())
    client = env.execute_async("no-cp")
    with pytest.raises(RuntimeError, match="checkpointing"):
        client.trigger_savepoint("/tmp/nowhere")
    PausingSource.released = True
    client.wait(30.0)


def test_operator_state_resplit_on_rescale():
    """Direct check of the runtime-level operator-state round robin:
    2 old subtasks' list state re-splits across 3 new subtasks with
    nothing lost or duplicated."""
    import pickle

    from flink_tpu.state.operator_state import (
        SPLIT_DISTRIBUTE,
        OperatorStateSnapshot,
    )

    old = [OperatorStateSnapshot(
        {"offsets": (SPLIT_DISTRIBUTE,
                     pickle.dumps([f"p{i}-{j}" for j in range(4)]))}, {})
        for i in range(2)]
    parts = OperatorStateSnapshot.redistribute(old, 3)
    gathered = []
    for p in parts:
        mode, blob = p.list_states["offsets"]
        gathered.extend(pickle.loads(blob))
    assert sorted(gathered) == sorted(
        f"p{i}-{j}" for i in range(2) for j in range(4))
    sizes = [len(pickle.loads(p.list_states["offsets"][1])) for p in parts]
    assert max(sizes) - min(sizes) <= 1  # balanced round robin


def test_function_state_assigned_exactly_once_on_rescale():
    """CheckpointedFunction state (2PC pending transactions, source
    offsets) must land on exactly ONE new subtask — broadcast would
    recover-and-commit every pending transaction once per subtask."""
    from flink_tpu.runtime.local import compute_restore_assignments

    snaps = {
        (1, i): {"operators": {"op": {"keyed": f"kg-{i}",
                                      "function": {"txn": i}}}}
        for i in range(2)
    }
    restore = {"tasks": snaps, "parallelisms": {1: 2}}
    mapping = compute_restore_assignments({1: 3}, restore)  # scale up
    seen = []
    for tk, snap_list in mapping.items():
        for s in snap_list:
            op = s["operators"].get("op", {})
            if "function" in op:
                seen.append((tk, op["function"]["txn"]))
    assert sorted(t for _, t in seen) == [0, 1]  # each exactly once
    assert len({tk for tk, _ in seen}) == 2      # on distinct subtasks
    # keyed state still reaches every new subtask (range-filtered)
    for tk, snap_list in mapping.items():
        keyed = [s["operators"]["op"].get("keyed") for s in snap_list
                 if "keyed" in s["operators"].get("op", {})]
        assert sorted(k for k in keyed if k) == ["kg-0", "kg-1"]

    # scale DOWN: 3 old states onto 2 new subtasks, still exactly once
    snaps3 = {
        (1, i): {"operators": {"op": {"function": {"txn": i}}}}
        for i in range(3)
    }
    mapping2 = compute_restore_assignments(
        {1: 2}, {"tasks": snaps3, "parallelisms": {1: 3}})
    seen2 = [op["function"]["txn"]
             for snap_list in mapping2.values() for s in snap_list
             for op in [s["operators"].get("op", {})] if "function" in op]
    assert sorted(seen2) == [0, 1, 2]


def test_stateful_orphan_fails_restore_unless_allowed():
    """Snapshot state whose operator uid matches nothing in the new
    topology FAILS the restore; allow_non_restored=True downgrades to
    a warning and drops it; stateless unmatched snapshots drop
    silently (ref: --allowNonRestoredState)."""
    import warnings

    from flink_tpu.runtime.local import compute_restore_assignments

    restore = {"tasks": {(7, 0): {"operators": {
        "stateful-op": {"my_engine_state": {"x": 1}},
        "stateless-op": {},
    }}}}
    new_uids = {1: {"some-other-op"}}
    with pytest.raises(RuntimeError, match="stateful-op"):
        compute_restore_assignments({1: 1}, restore,
                                    vertex_uids=new_uids)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = compute_restore_assignments({1: 1}, restore,
                                          vertex_uids=new_uids,
                                          allow_non_restored=True)
    assert any("DROPPED" in str(x.message) for x in w)
    assert out == {}

    # stateless orphans never raise or warn
    restore2 = {"tasks": {(7, 0): {"operators": {"stateless-op": {}}}}}
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        assert compute_restore_assignments(
            {1: 1}, restore2, vertex_uids=new_uids) == {}
    assert not w2


def test_chained_operator_orphan_detected_inside_matched_vertex():
    """Operator-granular orphan check: a vertex can match via one
    pinned uid while a chained operator's shifted uid strands its
    state — that must fail too, not silently filter."""
    from flink_tpu.runtime.local import compute_restore_assignments

    restore = {"tasks": {(3, 0): {"operators": {
        "pinned-agg": {"engine": {"windows": 1}},
        "op-4-sink": {"function": {"pending": ["txn"]}},
    }}}}
    # the new vertex carries the pinned uid but the sink became op-3
    new_uids = {2: {"pinned-agg", "op-3-sink"}}
    with pytest.raises(RuntimeError, match="op-4-sink"):
        compute_restore_assignments({2: 1}, restore,
                                    vertex_uids=new_uids)
