"""Log-structured tumbling engine: differential tests vs the
device-resident scatter engine and exact references.

The log engine must produce the same fires as VectorizedTumblingWindows
(same windows, same keys, same estimates within float tolerance) — the
two tiers implement one semantics (WindowOperator.processElement /
emitWindowContents, WindowOperator.java:291,544) with different
mechanisms (scatter-resident registers vs sort+segmented reduction).
"""

import numpy as np
import pytest

import flink_tpu.native as nat
from flink_tpu.ops.device_agg import SumAggregate
from flink_tpu.ops.sketches import HyperLogLogAggregate
from flink_tpu.streaming.log_windows import LogStructuredTumblingWindows
from flink_tpu.streaming.vectorized import (
    VectorizedTumblingWindows,
    hash_keys_np,
)

pytestmark = pytest.mark.skipif(not nat.available(),
                                reason="native runtime unavailable")


def synth(n, n_keys, t_span, seed):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, n).astype(np.uint64)
    ts = np.sort(rng.integers(0, t_span, n).astype(np.int64))
    users = rng.integers(0, 2 ** 63, n).astype(np.uint64)
    return keys, ts, users


def fire_map(engine_emitted):
    return {(int(k), s): float(r) for k, r, s, e in engine_emitted}


def test_hll_log_matches_scatter_engine():
    n, n_keys = 20_000, 700
    keys, ts, users = synth(n, n_keys, 5000, seed=3)
    vh = hash_keys_np(users)
    agg = HyperLogLogAggregate(precision=10)

    vec = VectorizedTumblingWindows(agg, 1000, initial_capacity=2048)
    vec.process_batch(keys, ts, None, key_hashes=keys, value_hashes=vh)
    vec.flush()
    vec.advance_watermark(10_000)

    log = LogStructuredTumblingWindows(agg, 1000)
    log.process_batch(keys, ts, None, value_hashes=vh)
    log.advance_watermark(10_000)

    got = fire_map(log.emitted)
    want = fire_map(vec.emitted)
    assert set(got) == set(want)
    for k in want:
        assert got[k] == pytest.approx(want[k], rel=1e-3)


def test_sum_log_exact_counts():
    n, n_keys = 50_000, 300
    keys, ts, _ = synth(n, n_keys, 3000, seed=5)
    agg = SumAggregate(np.float64)
    eng = LogStructuredTumblingWindows(agg, 1000)
    eng.process_batch(keys, ts, np.ones(n))
    eng.advance_watermark(10_000)
    got = fire_map(eng.emitted)
    # exact reference
    want = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        want[(k, t - t % 1000)] = want.get((k, t - t % 1000), 0) + 1
    assert got == want


def test_late_records_dropped():
    agg = SumAggregate(np.float64)
    eng = LogStructuredTumblingWindows(agg, 1000)
    eng.process_batch(np.array([1, 2], np.uint64), np.array([100, 900]),
                      np.ones(2))
    assert eng.advance_watermark(999) == 2
    # window [0, 1000) already fired -> late, dropped
    eng.process_batch(np.array([3], np.uint64), np.array([500]), np.ones(1))
    assert eng.num_late_dropped == 1
    eng.process_batch(np.array([4], np.uint64), np.array([1500]), np.ones(1))
    assert eng.advance_watermark(2000) == 1


def test_device_finish_tier_matches_host():
    n, n_keys = 30_000, 500
    keys, ts, users = synth(n, n_keys, 2000, seed=7)
    vh = hash_keys_np(users)
    agg = HyperLogLogAggregate(precision=12)
    host = LogStructuredTumblingWindows(agg, 1000, finish_tier="host")
    dev = LogStructuredTumblingWindows(agg, 1000, finish_tier="device")
    for eng in (host, dev):
        eng.process_batch(keys, ts, None, value_hashes=vh)
        eng.advance_watermark(5000)
    got_h = fire_map(host.emitted)
    got_d = fire_map(dev.emitted)
    assert set(got_h) == set(got_d)
    for k in got_h:
        assert got_d[k] == pytest.approx(got_h[k], rel=1e-3)


def test_compaction_preserves_results():
    n, n_keys = 40_000, 200
    keys, ts, users = synth(n, n_keys, 900, seed=9)  # single window
    vh = hash_keys_np(users)
    agg = HyperLogLogAggregate(precision=10)
    a = LogStructuredTumblingWindows(agg, 1000)
    b = LogStructuredTumblingWindows(agg, 1000, compact_threshold=1000)
    for eng in (a, b):
        for i in range(0, n, 4096):
            sl = slice(i, i + 4096)
            eng.process_batch(keys[sl], ts[sl], None, value_hashes=vh[sl])
        eng.advance_watermark(2000)
    assert b.windows == {}
    got_a, got_b = fire_map(a.emitted), fire_map(b.emitted)
    assert set(got_a) == set(got_b)
    for k in got_a:
        assert got_b[k] == pytest.approx(got_a[k], rel=1e-6)


def test_snapshot_restore_mid_window():
    n, n_keys = 20_000, 150
    keys, ts, users = synth(n, n_keys, 1800, seed=11)
    vh = hash_keys_np(users)
    agg = HyperLogLogAggregate(precision=10)
    ref = LogStructuredTumblingWindows(agg, 1000)
    ref.process_batch(keys, ts, None, value_hashes=vh)
    ref.advance_watermark(3000)

    half = n // 2
    a = LogStructuredTumblingWindows(agg, 1000)
    a.process_batch(keys[:half], ts[:half], None, value_hashes=vh[:half])
    snap = a.snapshot()
    b = LogStructuredTumblingWindows(agg, 1000)
    b.restore(snap)
    b.process_batch(keys[half:], ts[half:], None, value_hashes=vh[half:])
    b.advance_watermark(3000)
    assert fire_map(b.emitted) == fire_map(ref.emitted)


def test_non_integer_keys_rejected():
    eng = LogStructuredTumblingWindows(SumAggregate(np.float64), 1000)
    with pytest.raises(TypeError):
        eng.process_batch(np.array(["a", "b"], dtype=object),
                          np.array([1, 2]), np.ones(2))


# ---------------------------------------------------------------------
# sliding / session log engines
# ---------------------------------------------------------------------

from flink_tpu.ops.sketches import (  # noqa: E402
    CountMinSketchAggregate,
    QuantileSketchAggregate,
)
from flink_tpu.streaming.log_windows import (  # noqa: E402
    LogStructuredSessionWindows,
    LogStructuredSlidingWindows,
)
from flink_tpu.streaming.vectorized import VectorizedSlidingWindows  # noqa: E402
from flink_tpu.streaming.vectorized_sessions import (  # noqa: E402
    VectorizedSessionWindows,
)


def test_sliding_sum_log_matches_vectorized():
    n, n_keys = 30_000, 400
    keys, ts, _ = synth(n, n_keys, 8000, seed=13)
    agg = SumAggregate(np.float64)
    vec = VectorizedSlidingWindows(agg, 3000, 1000, initial_capacity=4096)
    vec.process_batch(keys, ts, np.ones(n), key_hashes=keys)
    vec.advance_watermark(20_000)
    log = LogStructuredSlidingWindows(agg, 3000, 1000)
    log.process_batch(keys, ts, np.ones(n))
    log.advance_watermark(20_000)
    got = {(int(k), s, e): float(r) for k, r, s, e in log.emitted}
    want = {(int(k), s, e): float(r) for k, r, s, e in vec.emitted}
    assert got == want


def test_sliding_sum_log_incremental_watermarks():
    n, n_keys = 30_000, 250
    keys, ts, _ = synth(n, n_keys, 9000, seed=15)
    agg = SumAggregate(np.float64)
    ref = LogStructuredSlidingWindows(agg, 3000, 1000)
    ref.process_batch(keys, ts, np.ones(n))
    ref.advance_watermark(20_000)
    inc = LogStructuredSlidingWindows(agg, 3000, 1000)
    # feed time-ordered chunks with interleaved watermarks
    CH = 5000
    for i in range(0, n, CH):
        sl = slice(i, i + CH)
        inc.process_batch(keys[sl], ts[sl], np.ones(len(keys[sl])))
        inc.advance_watermark(int(ts[sl][-1]) - 1)
    inc.advance_watermark(20_000)
    got = {(int(k), s, e): float(r) for k, r, s, e in inc.emitted}
    want = {(int(k), s, e): float(r) for k, r, s, e in ref.emitted}
    assert got == want


def test_sliding_quantile_log_close_to_vectorized():
    n, n_keys = 20_000, 50
    rng = np.random.default_rng(17)
    keys = rng.integers(0, n_keys, n).astype(np.uint64)
    ts = np.sort(rng.integers(0, 4000, n).astype(np.int64))
    vals = rng.lognormal(3.0, 1.0, n).astype(np.float32)
    agg = QuantileSketchAggregate(quantiles=(0.5, 0.99),
                                  relative_accuracy=0.05,
                                  min_value=1e-3, max_value=1e6)
    vec = VectorizedSlidingWindows(agg, 2000, 1000, initial_capacity=2048)
    vec.process_batch(keys, ts, vals, key_hashes=keys)
    vec.advance_watermark(10_000)
    log = LogStructuredSlidingWindows(agg, 2000, 1000)
    log.process_batch(keys, ts, vals)
    log.advance_watermark(10_000)
    want = {(int(k), s, e): np.asarray(r) for k, r, s, e in vec.emitted}
    got = {(int(k), s, e): np.asarray(r) for k, r, s, e in log.emitted}
    assert set(got) == set(want)
    # bucketing is f32 on both sides but log/exp rounding may flip a
    # boundary value by one bucket: allow one-bucket (~2*rel_acc)
    # slack per quantile
    for k in want:
        assert np.allclose(got[k], want[k], rtol=0.12), (k, got[k], want[k])


def test_session_log_matches_vectorized():
    n, n_keys = 25_000, 300
    rng = np.random.default_rng(19)
    keys = rng.integers(0, n_keys, n).astype(np.uint64)
    ts = np.sort(rng.integers(0, 60_000, n).astype(np.int64))
    users = rng.integers(0, 2 ** 63, n).astype(np.uint64)
    vh = hash_keys_np(users)
    agg = CountMinSketchAggregate(depth=4, width=64)
    vec = VectorizedSessionWindows(agg, 500, initial_capacity=4096)
    log = LogStructuredSessionWindows(agg, 500)
    CH = 5000
    for eng in (vec, log):
        for i in range(0, n, CH):
            sl = slice(i, i + CH)
            eng.process_batch(keys[sl], ts[sl],
                              np.ones(len(keys[sl]), np.float32),
                              key_hashes=keys[sl], value_hashes=vh[sl])
            if hasattr(eng, "flush"):
                eng.flush()
            eng.advance_watermark(int(ts[sl][-1]) - 1)
        eng.advance_watermark(200_000)
    got = {(int(k), s, e): int(r) for k, r, s, e in log.emitted}
    want = {(int(k), s, e): int(r) for k, r, s, e in vec.emitted}
    assert got == want


def test_session_abutting_events_merge():
    """Events exactly gap apart share a session (TimeWindow.intersects
    is inclusive — the scalar operator merges abutting windows,
    test_session_bridge_merge)."""
    agg = CountMinSketchAggregate(depth=2, width=32)
    for eng in (VectorizedSessionWindows(agg, 1000, initial_capacity=64),
                LogStructuredSessionWindows(agg, 1000)):
        eng.process_batch(np.array([7, 7], np.uint64),
                          np.array([0, 1000], np.int64),
                          np.ones(2, np.float32),
                          value_hashes=np.array([11, 12], np.uint64))
        eng.advance_watermark(10_000)
        assert [(int(k), int(r), s, e) for k, r, s, e in eng.emitted] == \
            [(7, 2, 0, 2000)], type(eng).__name__


def test_session_log_snapshot_restore():
    n, n_keys = 8000, 100
    rng = np.random.default_rng(23)
    keys = rng.integers(0, n_keys, n).astype(np.uint64)
    ts = np.sort(rng.integers(0, 20_000, n).astype(np.int64))
    vh = rng.integers(0, 2 ** 63, n).astype(np.uint64)
    agg = CountMinSketchAggregate(depth=2, width=32)
    ref = LogStructuredSessionWindows(agg, 400)
    ref.process_batch(keys, ts, np.ones(n, np.float32), value_hashes=vh)
    ref.advance_watermark(50_000)
    a = LogStructuredSessionWindows(agg, 400)
    a.process_batch(keys[:4000], ts[:4000], np.ones(4000, np.float32),
                    value_hashes=vh[:4000])
    b = LogStructuredSessionWindows(agg, 400)
    b.restore(a.snapshot())
    b.process_batch(keys[4000:], ts[4000:], np.ones(4000, np.float32),
                    value_hashes=vh[4000:])
    b.advance_watermark(50_000)
    assert sorted(map(tuple, b.emitted)) == sorted(map(tuple, ref.emitted))


def test_sliding_snapshot_preserves_fired_horizon():
    """A restored sliding engine must not re-fire already-fired
    windows from pruned panes (code-review regression)."""
    agg = SumAggregate(np.float64)
    a = LogStructuredSlidingWindows(agg, 3000, 1000)
    keys = np.array([1, 1, 1, 1, 1], np.uint64)
    ts = np.array([500, 1500, 2500, 3500, 4500], np.int64)
    a.process_batch(keys, ts, np.ones(5))
    a.advance_watermark(4999)
    fired_before = {(s, e) for _, _, s, e in a.emitted}
    b = LogStructuredSlidingWindows(agg, 3000, 1000)
    b.restore(a.snapshot())
    b.advance_watermark(7999)
    refired = {(s, e) for _, _, s, e in b.emitted} & fired_before
    assert not refired, refired
    # and the still-due windows fire exactly once with full data
    ref = LogStructuredSlidingWindows(agg, 3000, 1000)
    ref.process_batch(keys, ts, np.ones(5))
    ref.advance_watermark(4999)
    ref.emitted.clear()
    ref.advance_watermark(7999)
    assert sorted(map(tuple, b.emitted)) == sorted(map(tuple, ref.emitted))


def test_sum_dense_table_spill_to_log():
    """The adaptive sum state must produce identical results whether it
    stays dense or spills to log form mid-window (incl. key 0)."""
    from flink_tpu.streaming.log_windows import _SumTabLog
    rng = np.random.default_rng(29)
    keys = rng.integers(0, 5000, 40_000).astype(np.uint64)
    keys[:10] = 0  # key 0 exercises the probe-table zero remap
    vals = rng.random(40_000)
    dense = _SumTabLog(max_distinct=1 << 16)
    spill = _SumTabLog(max_distinct=1 << 10)  # forces mid-stream spill
    for st in (dense, spill):
        for i in range(0, 40_000, 4096):
            st.append(keys[i:i + 4096], vals[i:i + 4096])
    assert spill.log is not None and dense.log is None
    dk, (dv,) = dense.concat()
    sk, (sv,) = spill.concat()
    want = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        want[k] = want.get(k, 0.0) + v
    for ks, vs in ((dk, dv), (sk, sv)):
        got_k, got_v = nat.sum_log_fire(ks, vs)
        got = dict(zip(got_k.tolist(), got_v.tolist()))
        assert set(got) == set(want)
        for k in want:
            assert got[k] == pytest.approx(want[k], rel=1e-9)


def test_sum_key_zero_and_sentinel_distinct():
    """Key 0 and the probe table's internal remap constant must stay
    distinct groups (code-review regression: they merged)."""
    sentinel = 0x9E3779B97F4A7C15
    eng = LogStructuredTumblingWindows(SumAggregate(np.float64), 1000)
    eng.process_batch(np.array([0, sentinel, 0], np.uint64),
                      np.array([10, 20, 30], np.int64),
                      np.array([1.0, 10.0, 100.0]))
    eng.advance_watermark(5000)
    got = {int(k): float(r) for k, r, s, e in eng.emitted}
    assert got == {0: 101.0, sentinel: 10.0}


def test_signed_negative_keys_roundtrip():
    """int64 keys (incl. negatives) group exactly and emit unchanged."""
    agg = SumAggregate(np.float64)
    eng = LogStructuredTumblingWindows(agg, 1000)
    keys = np.array([-5, 3, -5, -(2 ** 62)], np.int64)
    eng.process_batch(keys, np.array([10, 20, 30, 40]),
                      np.array([1.0, 2.0, 4.0, 8.0]))
    eng.advance_watermark(5000)
    got = {int(k): float(r) for k, r, s, e in eng.emitted}
    assert got == {-5: 5.0, 3: 2.0, -(2 ** 62): 8.0}
    # and through a snapshot/restore cycle
    eng2 = LogStructuredTumblingWindows(agg, 1000)
    eng2.process_batch(keys, np.array([10, 20, 30, 40]),
                       np.array([1.0, 2.0, 4.0, 8.0]))
    eng3 = LogStructuredTumblingWindows(agg, 1000)
    eng3.restore(eng2.snapshot())
    eng3.advance_watermark(5000)
    assert {int(k): float(r) for k, r, s, e in eng3.emitted} == got


def test_quantile_log_compaction_exact_and_bounded():
    """Count-cell compaction: quantiles with a tiny compact threshold
    equal the uncompacted run, and the compacted log is bounded by
    keys x buckets cells regardless of event volume."""
    import numpy as np

    from flink_tpu.ops.sketches import QuantileSketchAggregate
    from flink_tpu.streaming.log_windows import (
        LogStructuredTumblingWindows,
    )

    rng = np.random.default_rng(8)
    n, n_keys = 200_000, 40
    keys = rng.integers(0, n_keys, n).astype(np.int64)
    ts = np.sort(rng.integers(0, 1000, n)).astype(np.int64)
    vals = rng.gamma(2.0, 25.0, n)

    def run(threshold):
        agg = QuantileSketchAggregate(quantiles=(0.5, 0.9, 0.99))
        eng = LogStructuredTumblingWindows(agg, 1000,
                                           compact_threshold=threshold)
        half = n // 2
        eng.process_batch(keys[:half], ts[:half], vals[:half])
        max_cells = max((lg.count for lg in eng.windows.values()),
                        default=0)
        eng.process_batch(keys[half:], ts[half:], vals[half:])
        eng.advance_watermark(10_000)
        return ({(int(k), int(s)): tuple(np.round(v, 9))
                 for k, v, s, _ in eng.emitted}, max_cells)

    got, cells_small = run(threshold=10_000)     # compacts repeatedly
    want, _ = run(threshold=1 << 30)             # never compacts
    assert got == want and len(got) == n_keys
    # bounded: after compaction the log holds at most keys x buckets
    agg = QuantileSketchAggregate(quantiles=(0.5,))
    assert cells_small <= 2 * n_keys * agg.buckets


def test_quantile_snapshot_upgrades_old_single_column_logs():
    """Pre-count-cell checkpoints logged (bucket,) only; restore
    upgrades them to count cells and keeps firing exactly."""
    import numpy as np

    from flink_tpu.ops.sketches import QuantileSketchAggregate
    from flink_tpu.streaming.log_windows import (
        LogStructuredTumblingWindows,
    )

    agg = QuantileSketchAggregate(quantiles=(0.5,))
    eng = LogStructuredTumblingWindows(agg, 1000)
    keys = np.arange(50, dtype=np.int64) % 5
    ts = np.zeros(50, np.int64)
    vals = np.linspace(1.0, 100.0, 50)
    eng.process_batch(keys, ts, vals)
    snap = eng.snapshot()
    # rewrite the snapshot into the OLD single-column format
    from flink_tpu.state.shared_registry import SharedChunk
    for start, chunk in snap["windows"].items():
        payload = chunk.payload if isinstance(chunk, SharedChunk) \
            else chunk
        payload["cols"] = [payload["cols"][0]]  # drop the count column
    eng2 = LogStructuredTumblingWindows(agg, 1000)
    eng2.restore(snap)
    for e in (eng, eng2):
        e.advance_watermark(10_000)
    got = {(int(k), int(s)): tuple(v) for k, v, s, _ in eng2.emitted}
    want = {(int(k), int(s)): tuple(v) for k, v, s, _ in eng.emitted}
    assert got == want and len(got) == 5
