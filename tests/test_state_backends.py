"""State-backend contract suite, run against heap AND tpu backends.

Ports the intent of the reference's StateBackendTestBase.java (3,726
LoC abstract suite run against every backend — SURVEY.md §4.3): value/
list/map/reducing/aggregating semantics, namespaces, snapshot/restore,
rescale re-split, and (tpu-only) device/heap differential equivalence.
"""

import numpy as np
import pytest

from flink_tpu.core.config import Configuration
from flink_tpu.core.keygroups import (
    KeyGroupRange,
    assign_to_key_group,
    compute_key_group_range_for_operator_index,
)
from flink_tpu.core.state import (
    AggregatingStateDescriptor,
    ListStateDescriptor,
    MapStateDescriptor,
    ReducingStateDescriptor,
    ValueStateDescriptor,
)
from flink_tpu.ops.device_agg import CountAggregate, SumAggregate
from flink_tpu.ops.sketches import HyperLogLogAggregate
from flink_tpu.state import (
    HeapKeyedStateBackend,
    TpuKeyedStateBackend,
    load_state_backend,
)
from flink_tpu.state.operator_state import (
    OperatorStateBackend,
    OperatorStateSnapshot,
)

MAX_PAR = 128
FULL_RANGE = KeyGroupRange(0, MAX_PAR - 1)

BACKENDS = ["heap", "tpu"]


def make_backend(name):
    return load_state_backend(name, FULL_RANGE, MAX_PAR)


@pytest.fixture(params=BACKENDS)
def backend(request):
    b = make_backend(request.param)
    yield b
    b.dispose()


# ---------------------------------------------------------------------
# loader
# ---------------------------------------------------------------------

def test_loader_config_switch():
    cfg = Configuration()
    assert isinstance(load_state_backend(cfg, FULL_RANGE, MAX_PAR),
                      HeapKeyedStateBackend)
    cfg.set("state.backend", "tpu")
    assert isinstance(load_state_backend(cfg, FULL_RANGE, MAX_PAR),
                      TpuKeyedStateBackend)
    with pytest.raises(ValueError):
        load_state_backend("nope", FULL_RANGE, MAX_PAR)


# ---------------------------------------------------------------------
# value / list / map state
# ---------------------------------------------------------------------

def test_value_state(backend):
    st = backend.get_or_create_keyed_state(ValueStateDescriptor("v"))
    backend.set_current_key("a")
    assert st.value() is None
    st.update(42)
    assert st.value() == 42
    backend.set_current_key("b")
    assert st.value() is None
    st.update(7)
    backend.set_current_key("a")
    assert st.value() == 42
    st.clear()
    assert st.value() is None
    backend.set_current_key("b")
    assert st.value() == 7


def test_value_state_default(backend):
    st = backend.get_or_create_keyed_state(
        ValueStateDescriptor("vd", default_value=99))
    backend.set_current_key("x")
    assert st.value() == 99
    st.update(1)
    assert st.value() == 1


def test_list_state(backend):
    st = backend.get_or_create_keyed_state(ListStateDescriptor("l"))
    backend.set_current_key("k1")
    assert st.get() is None
    st.add(1)
    st.add(2)
    st.add_all([3, 4])
    assert list(st.get()) == [1, 2, 3, 4]
    st.update([9])
    assert list(st.get()) == [9]
    backend.set_current_key("k2")
    assert st.get() is None
    backend.set_current_key("k1")
    st.clear()
    assert st.get() is None


def test_map_state(backend):
    st = backend.get_or_create_keyed_state(MapStateDescriptor("m"))
    backend.set_current_key("k")
    assert st.is_empty()
    st.put("a", 1)
    st.put_all({"b": 2, "c": 3})
    assert st.get("a") == 1
    assert st.contains("b")
    assert not st.contains("z")
    assert sorted(st.keys()) == ["a", "b", "c"]
    assert sorted(st.values()) == [1, 2, 3]
    st.remove("a")
    assert st.get("a") is None
    assert sorted(dict(st.entries()).keys()) == ["b", "c"]
    st.clear()
    assert st.is_empty()


# ---------------------------------------------------------------------
# reducing / aggregating
# ---------------------------------------------------------------------

def test_reducing_state(backend):
    st = backend.get_or_create_keyed_state(
        ReducingStateDescriptor("r", lambda a, b: a + b))
    backend.set_current_key("k")
    assert st.get() is None
    st.add(5)
    st.add(6)
    assert st.get() == 11
    backend.set_current_key("other")
    st.add(1)
    assert st.get() == 1


def test_aggregating_state_device_sum(backend):
    st = backend.get_or_create_keyed_state(
        AggregatingStateDescriptor("agg", SumAggregate(np.float32)))
    backend.set_current_key("k")
    assert st.get() is None
    st.add(1.5)
    st.add(2.5)
    assert st.get() == pytest.approx(4.0)
    backend.set_current_key("j")
    st.add(10.0)
    assert st.get() == pytest.approx(10.0)
    backend.set_current_key("k")
    st.clear()
    assert st.get() is None


def test_aggregating_state_namespaces(backend):
    st = backend.get_or_create_keyed_state(
        AggregatingStateDescriptor("aggns", CountAggregate()))
    backend.set_current_key("k")
    st.set_current_namespace(("w", 0))
    st.add(object())
    st.add(object())
    st.set_current_namespace(("w", 1))
    st.add(object())
    assert st.get() == 1
    st.set_current_namespace(("w", 0))
    assert st.get() == 2


def test_merge_namespaces(backend):
    st = backend.get_or_create_keyed_state(
        AggregatingStateDescriptor("m_agg", SumAggregate(np.float32)))
    backend.set_current_key("k")
    for ns, v in [(("s", 1), 1.0), (("s", 2), 2.0), (("s", 3), 4.0)]:
        st.set_current_namespace(ns)
        st.add(v)
    st.merge_namespaces(("s", 9), [("s", 1), ("s", 2), ("s", 3)])
    st.set_current_namespace(("s", 9))
    assert st.get() == pytest.approx(7.0)
    for ns in [("s", 1), ("s", 2), ("s", 3)]:
        st.set_current_namespace(ns)
        assert st.get() is None


def test_hll_aggregating(backend):
    st = backend.get_or_create_keyed_state(
        AggregatingStateDescriptor("hll", HyperLogLogAggregate(precision=10)))
    backend.set_current_key("page1")
    for i in range(1000):
        st.add(f"user-{i}")
    est = st.get()
    assert abs(est - 1000) / 1000 < 0.12


def test_get_keys(backend):
    st = backend.get_or_create_keyed_state(
        AggregatingStateDescriptor("gk", CountAggregate()))
    for k in ["a", "b", "c"]:
        backend.set_current_key(k)
        st.set_current_namespace("ns0")
        st.add(1)
    assert sorted(backend.get_keys("gk", "ns0")) == ["a", "b", "c"]
    assert backend.get_keys("gk", "nsX") == []


# ---------------------------------------------------------------------
# snapshot / restore / rescale
# ---------------------------------------------------------------------

def _populate(backend, n=50):
    v = backend.get_or_create_keyed_state(ValueStateDescriptor("v"))
    agg = backend.get_or_create_keyed_state(
        AggregatingStateDescriptor("agg", SumAggregate(np.float32)))
    for i in range(n):
        backend.set_current_key(f"key-{i}")
        v.update(i)
        agg.set_current_namespace("w0")
        agg.add(float(i))
        agg.add(1.0)


def _check(backend, n=50):
    v = backend.get_or_create_keyed_state(ValueStateDescriptor("v"))
    agg = backend.get_or_create_keyed_state(
        AggregatingStateDescriptor("agg", SumAggregate(np.float32)))
    for i in range(n):
        backend.set_current_key(f"key-{i}")
        assert v.value() == i
        agg.set_current_namespace("w0")
        assert agg.get() == pytest.approx(i + 1.0)


@pytest.mark.parametrize("name", BACKENDS)
def test_snapshot_restore_roundtrip(name):
    b1 = make_backend(name)
    _populate(b1)
    snap = b1.snapshot()
    assert snap.total_bytes > 0
    b2 = make_backend(name)
    # bind states first (descriptors must be known before restore)
    b2.get_or_create_keyed_state(ValueStateDescriptor("v"))
    b2.get_or_create_keyed_state(
        AggregatingStateDescriptor("agg", SumAggregate(np.float32)))
    b2.restore([snap])
    _check(b2)


@pytest.mark.parametrize("name", BACKENDS)
def test_cross_backend_restore(name):
    """heap snapshot restores into tpu backend and vice versa — the
    `state.backend` switch must be transparent across restarts."""
    other = "tpu" if name == "heap" else "heap"
    b1 = make_backend(name)
    _populate(b1, 20)
    snap = b1.snapshot()
    b2 = make_backend(other)
    b2.get_or_create_keyed_state(ValueStateDescriptor("v"))
    b2.get_or_create_keyed_state(
        AggregatingStateDescriptor("agg", SumAggregate(np.float32)))
    b2.restore([snap])
    _check(b2, 20)


@pytest.mark.parametrize("name", BACKENDS)
def test_rescale_resplit(name):
    """Snapshot at parallelism 1, restore at parallelism 2: each new
    subtask takes only the chunks in its key-group range (ref:
    RescalingITCase, StateAssignmentOperation)."""
    b1 = make_backend(name)
    _populate(b1, 60)
    snap = b1.snapshot()

    parts = []
    for idx in range(2):
        rng = compute_key_group_range_for_operator_index(MAX_PAR, 2, idx)
        b = load_state_backend(name, rng, MAX_PAR)
        b.get_or_create_keyed_state(ValueStateDescriptor("v"))
        b.get_or_create_keyed_state(
            AggregatingStateDescriptor("agg", SumAggregate(np.float32)))
        b.restore([snap])
        parts.append((rng, b))

    seen = set()
    for i in range(60):
        key = f"key-{i}"
        kg = assign_to_key_group(key, MAX_PAR)
        owner = [b for rng, b in parts if rng.contains(kg)]
        assert len(owner) == 1
        b = owner[0]
        v = b.get_or_create_keyed_state(ValueStateDescriptor("v"))
        b.set_current_key(key)
        assert v.value() == i
        seen.add(key)
    assert len(seen) == 60
    # both subtasks actually own some keys
    for rng, b in parts:
        assert any(rng.contains(assign_to_key_group(f"key-{i}", MAX_PAR))
                   for i in range(60))


# ---------------------------------------------------------------------
# tpu-specific: batched API + differential vs heap
# ---------------------------------------------------------------------

def test_tpu_add_batch_matches_heap():
    rng_keys = [f"k{i % 17}" for i in range(500)]
    vals = np.arange(500, dtype=np.float32)

    heap = make_backend("heap")
    hs = heap.get_or_create_keyed_state(
        AggregatingStateDescriptor("s", SumAggregate(np.float32)))
    for k, v in zip(rng_keys, vals):
        heap.set_current_key(k)
        hs.set_current_namespace("w")
        hs.add(float(v))

    tpu = make_backend("tpu")
    ts = tpu.get_or_create_keyed_state(
        AggregatingStateDescriptor("s", SumAggregate(np.float32)))
    ts.add_batch(rng_keys, "w", vals)

    for k in set(rng_keys):
        heap.set_current_key(k)
        hs.set_current_namespace("w")
        tpu.set_current_key(k)
        ts.set_current_namespace("w")
        assert ts.get() == pytest.approx(hs.get()), k


def test_tpu_capacity_growth():
    tpu = TpuKeyedStateBackend(FULL_RANGE, MAX_PAR, initial_capacity=8)
    st = tpu.get_or_create_keyed_state(
        AggregatingStateDescriptor("g", CountAggregate()))
    for i in range(100):
        tpu.set_current_key(i)
        st.add(1)
    for i in range(100):
        tpu.set_current_key(i)
        assert st.get() == 1


def test_tpu_get_batch():
    tpu = make_backend("tpu")
    st = tpu.get_or_create_keyed_state(
        AggregatingStateDescriptor("gb", SumAggregate(np.float32)))
    keys = [f"k{i}" for i in range(10)]
    st.add_batch(keys, "w", np.arange(10, dtype=np.float32))
    res, found = st.get_batch(keys + ["missing"], "w")
    assert found[:10].all() and not found[10]
    np.testing.assert_allclose(res[:10], np.arange(10, dtype=np.float32))


# ---------------------------------------------------------------------
# operator state
# ---------------------------------------------------------------------

def test_operator_list_state_roundtrip():
    b = OperatorStateBackend()
    ls = b.get_list_state("offsets")
    ls.add_all([("p0", 5), ("p1", 7)])
    bs = b.get_broadcast_state("rules")
    bs.put("r1", "drop")
    snap = b.snapshot()

    b2 = OperatorStateBackend()
    b2.restore(snap)
    assert b2.get_list_state("offsets").get() == [("p0", 5), ("p1", 7)]
    assert b2.get_broadcast_state("rules").get("r1") == "drop"


def test_operator_state_redistribute():
    snaps = []
    for subtask in range(2):
        b = OperatorStateBackend()
        b.get_list_state("split").add_all([f"s{subtask}-{i}" for i in range(3)])
        b.get_union_list_state("union").add(f"u{subtask}")
        snaps.append(b.snapshot())

    parts = OperatorStateSnapshot.redistribute(snaps, 3)
    assert len(parts) == 3
    backends = []
    for p in parts:
        b = OperatorStateBackend()
        b.restore(p)
        backends.append(b)
    all_split = sorted(sum((b.get_list_state("split").get() for b in backends), []))
    assert all_split == sorted(f"s{s}-{i}" for s in range(2) for i in range(3))
    for b in backends:
        assert sorted(b.get_union_list_state("union").get()) == ["u0", "u1"]


# ---------------------------------------------------------------------
# regression tests for review findings
# ---------------------------------------------------------------------

def test_restore_drops_inflight_pending_writes():
    """Pre-restore buffered writes must not leak into restored state."""
    tpu = make_backend("tpu")
    st = tpu.get_or_create_keyed_state(
        AggregatingStateDescriptor("p", SumAggregate(np.float32)))
    tpu.set_current_key("a")
    st.add(1.0)
    snap = tpu.snapshot()  # flushes: a=1.0
    tpu.set_current_key("b")
    st.add(100.0)          # in-flight, never snapshotted
    tpu.restore([snap])
    tpu.set_current_key("c")
    st.add(1.0)
    assert st.get() == pytest.approx(1.0)  # not 101.0
    tpu.set_current_key("a")
    assert st.get() == pytest.approx(1.0)


def test_merge_empty_namespaces_leaves_no_state(backend):
    st = backend.get_or_create_keyed_state(
        AggregatingStateDescriptor("me", SumAggregate(np.float32)))
    backend.set_current_key("k")
    st.merge_namespaces(("w", 9), [("w", 1), ("w", 2)])
    st.set_current_namespace(("w", 9))
    assert st.get() is None


def test_nan_inf_keys():
    b = make_backend("heap")
    st = b.get_or_create_keyed_state(ValueStateDescriptor("f"))
    for k in [float("nan"), float("inf"), float("-inf"), 1.5]:
        b.set_current_key(k)
        st.update("ok")
        assert st.value() == "ok"


def test_descriptor_rebind_type_mismatch(backend):
    backend.get_or_create_keyed_state(ValueStateDescriptor("dup"))
    with pytest.raises(ValueError):
        backend.get_or_create_keyed_state(MapStateDescriptor("dup"))


def test_restore_before_bind_then_late_bind():
    """Heap-format snapshot restored before the device descriptor is
    bound: accumulators must surface once the descriptor binds."""
    heap = make_backend("heap")
    hs = heap.get_or_create_keyed_state(
        AggregatingStateDescriptor("lb", SumAggregate(np.float32)))
    heap.set_current_key("x")
    hs.add(5.0)
    snap = heap.snapshot()

    tpu = make_backend("tpu")
    tpu.restore([snap])  # descriptor not bound yet
    st = tpu.get_or_create_keyed_state(
        AggregatingStateDescriptor("lb", SumAggregate(np.float32)))
    tpu.set_current_key("x")
    assert st.get() == pytest.approx(5.0)


# ---------------------------------------------------------------------
# serializer config snapshots + migration compatibility
# (ref: TypeSerializerConfigSnapshot / StateMigrationException)
# ---------------------------------------------------------------------

def test_serializer_compatibility_roundtrip():
    from flink_tpu.core.serialization import LongSerializer

    b1 = make_backend("heap")
    st = b1.get_or_create_keyed_state(
        ValueStateDescriptor("v", serializer=LongSerializer()))
    b1.set_current_key("k")
    st.update(7)
    snap = b1.snapshot()
    assert "serializers" in snap.meta
    assert snap.meta["serializers"]["v"].serializer_name == "LongSerializer"

    # same serializer: restores fine
    b2 = make_backend("heap")
    st2 = b2.get_or_create_keyed_state(
        ValueStateDescriptor("v", serializer=LongSerializer()))
    b2.restore([snap])
    b2.set_current_key("k")
    assert st2.value() == 7


def test_serializer_incompatibility_raises():
    from flink_tpu.core.serialization import (
        DoubleSerializer,
        LongSerializer,
        StateMigrationException,
    )

    b1 = make_backend("heap")
    st = b1.get_or_create_keyed_state(
        ValueStateDescriptor("v", serializer=LongSerializer()))
    b1.set_current_key("k")
    st.update(1)
    snap = b1.snapshot()

    b2 = make_backend("heap")
    b2.get_or_create_keyed_state(
        ValueStateDescriptor("v", serializer=DoubleSerializer()))
    with pytest.raises(StateMigrationException, match="'v'"):
        b2.restore([snap])


# ---------------------------------------------------------------------
# host-RAM spill tier (state > HBM — SURVEY §7 hard part; the
# disk-residency role RocksDB plays in the reference)
# ---------------------------------------------------------------------

def _mk_capped_device_state(cap=64, initial=16, microbatch=4):
    from flink_tpu.ops.device_agg import SumAggregate
    from flink_tpu.state.tpu_backend import TpuKeyedStateBackend

    b = TpuKeyedStateBackend(FULL_RANGE, MAX_PAR, initial_capacity=initial,
                             microbatch=microbatch, max_device_slots=cap)
    st = b.get_or_create_keyed_state(
        AggregatingStateDescriptor("agg", SumAggregate()))
    return b, st


def test_spill_tier_evicts_and_promotes():
    b, st = _mk_capped_device_state(cap=64, initial=16, microbatch=4)
    n_keys = 300  # far beyond the 64-slot device budget
    for k in range(n_keys):
        b.set_current_key(f"k{k}")
        st.add(float(k))
    st._flush()
    assert st.evictions > 0, "budget never triggered a spill"
    assert st.capacity <= 128  # soft cap: at most one emergency grow
    assert len(st.host_tier) > 0
    # every value readable — spilled entries promote transparently
    for k in range(n_keys):
        b.set_current_key(f"k{k}")
        assert st.get() == float(k)
    assert st.promotions > 0
    # adding to a previously spilled key keeps aggregating correctly
    b.set_current_key("k0")
    st.add(1000.0)
    assert st.get() == 1000.0


def test_spill_tier_snapshot_includes_host_tier():
    b, st = _mk_capped_device_state(cap=32, initial=8, microbatch=4)
    for k in range(200):
        b.set_current_key(f"k{k}")
        st.add(float(k))
    st._flush()
    assert st.host_tier, "expected spilled entries"
    snap = b.snapshot()
    # restore into an UNCAPPED backend: all 200 entries arrive
    from flink_tpu.ops.device_agg import SumAggregate
    from flink_tpu.state.tpu_backend import TpuKeyedStateBackend
    b2 = TpuKeyedStateBackend(FULL_RANGE, MAX_PAR)
    st2 = b2.get_or_create_keyed_state(
        AggregatingStateDescriptor("agg", SumAggregate()))
    b2.restore([snap])
    for k in range(200):
        b2.set_current_key(f"k{k}")
        assert st2.get() == float(k)
    # restore into a CAPPED backend: overflow lands in the host tier
    b3, st3 = _mk_capped_device_state(cap=32, initial=8, microbatch=4)
    b3.restore([snap])
    assert st3.host_tier
    for k in range(0, 200, 17):
        b3.set_current_key(f"k{k}")
        assert st3.get() == float(k)


def test_spill_tier_config_key():
    from flink_tpu.core.config import Configuration

    cfg = Configuration()
    cfg.set("state.backend", "tpu")
    cfg.set("state.backend.tpu.max-device-slots", 4096)
    backend = load_state_backend(cfg, FULL_RANGE, MAX_PAR)
    assert backend.max_device_slots == 4096


# ---------------------------------------------------------------------
# type extraction (TypeInformation / Types / the extractor analogue)
# ---------------------------------------------------------------------

def test_type_extraction_and_serializer_roundtrip():
    from flink_tpu.core.types import Types, extract_type_infos, type_info_of

    cases = [
        (7, "Long"), (1.5, "Double"), (True, "Boolean"),
        ("x", "String"), (b"b", "Bytes"),
        ((1, "a"), "Tuple2<Long, String>"),
        ([1, 2, 3], "List<Long>"),
        ({"k": 2.0}, "Map<String, Double>"),
    ]
    for sample, name in cases:
        info = type_info_of(sample)
        assert info.name == name, (sample, info.name)
        ser = info.create_serializer()
        assert ser.deserialize_from_bytes(
            ser.serialize_to_bytes(sample)) == sample

    # unknown types widen to the pickled generic type
    class Custom:
        pass

    assert type_info_of(Custom()).name == "Pickled"
    assert extract_type_infos([1, 2]).name == "Long"
    assert extract_type_infos([1, "a"]).name == "Pickled"
    # composite constructor
    t = Types.TUPLE(Types.LONG, Types.STRING)
    assert t.arity == 2 and not t.is_basic_type
