"""CEP: pattern builder + NFA semantics + stream integration
(ref: flink-cep NFAITCase/CEPITCase shapes — SURVEY.md §2.5, §2.9)."""

import numpy as np
import pytest

from flink_tpu.cep import CEP, NFA, Pattern
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.operators import OutputTag
from flink_tpu.streaming.sources import CollectSink


def _nfa(pattern):
    pattern.validate()
    return NFA(pattern)


def feed(nfa, events):
    """events: [(value, ts)] in time order → (matches, timeouts)."""
    all_m, all_t = [], []
    for v, t in events:
        m, to = nfa.advance(v, t)
        all_m.extend(m)
        all_t.extend(to)
    return all_m, all_t


def is_type(t):
    return lambda e: e[0] == t


# ---------------------------------------------------------------------
# NFA semantics
# ---------------------------------------------------------------------

def test_strict_next():
    p = (Pattern.begin("a").where(is_type("A"))
         .next("b").where(is_type("B")))
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("B", 2), 1)])
    assert m == [{"a": [("A", 1)], "b": [("B", 2)]}]
    # an intervening event breaks strict contiguity
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("C", 9), 1), (("B", 2), 2)])
    assert m == []


def test_followed_by_skips():
    p = (Pattern.begin("a").where(is_type("A"))
         .followed_by("b").where(is_type("B")))
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("C", 9), 1), (("B", 2), 2)])
    assert m == [{"a": [("A", 1)], "b": [("B", 2)]}]
    # skip-till-NEXT: only the first b completes a given a-run
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("B", 2), 1), (("B", 3), 2)])
    assert len(m) == 1


def test_followed_by_any_matches_all():
    p = (Pattern.begin("a").where(is_type("A"))
         .followed_by_any("b").where(is_type("B")))
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("B", 2), 1), (("B", 3), 2)])
    assert len(m) == 2


def test_conditions_and_or():
    p = (Pattern.begin("x")
         .where(lambda e: e[1] > 10)
         .or_(lambda e: e[0] == "VIP")
         .where(lambda e: e[0] != "D"))
    nfa = _nfa(p)
    m, _ = feed(nfa, [(("C", 50), 0), (("VIP", 0), 1), (("D", 99), 2),
                      (("C", 5), 3)])
    assert len(m) == 2  # ("C",50) and ("VIP",0); D fails AND, C5 fails OR


def test_times_exact():
    p = (Pattern.begin("a").where(is_type("A")).times(2)
         .followed_by("b").where(is_type("B")))
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("A", 2), 1), (("B", 3), 2)])
    assert m == [{"a": [("A", 1), ("A", 2)], "b": [("B", 3)]}]


def test_one_or_more_emits_every_extension():
    p = Pattern.begin("a").where(is_type("A")).one_or_more()
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("A", 2), 1)])
    # [A1], [A2], [A1 A2]
    assert len(m) == 3


def test_greedy_loop_concludes_on_break():
    p = (Pattern.begin("a").where(is_type("A")).one_or_more().greedy()
         .followed_by("b").where(is_type("B")))
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("A", 2), 1), (("B", 3), 2)])
    # greedy: the run from A1 absorbs maximally ([A1, A2]); no [A1]-only
    # match exists.  A separate run starting at A2 still matches (the
    # NO_SKIP after-match strategy starts a run at every event).
    assert {"a": [("A", 1), ("A", 2)], "b": [("B", 3)]} in m
    assert {"a": [("A", 1)], "b": [("B", 3)]} not in m


def test_optional_stage():
    p = (Pattern.begin("a").where(is_type("A"))
         .followed_by("m").where(is_type("M")).optional()
         .followed_by("b").where(is_type("B")))
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("B", 2), 1)])
    assert m == [{"a": [("A", 1)], "b": [("B", 2)]}]
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("M", 9), 1), (("B", 2), 2)])
    assert {"a": [("A", 1)], "m": [("M", 9)], "b": [("B", 2)]} in m


def test_not_next():
    p = (Pattern.begin("a").where(is_type("A"))
         .not_next("nb").where(is_type("B"))
         .followed_by("c").where(is_type("C")))
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("B", 9), 1), (("C", 2), 2)])
    assert m == []
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("X", 9), 1), (("C", 2), 2)])
    assert len(m) == 1


def test_not_followed_by_poisons():
    p = (Pattern.begin("a").where(is_type("A"))
         .not_followed_by("nb").where(is_type("B"))
         .followed_by("c").where(is_type("C")))
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("B", 9), 1), (("C", 2), 2)])
    assert m == []
    m, _ = feed(_nfa(p), [(("A", 1), 0), (("X", 9), 1), (("C", 2), 2)])
    assert len(m) == 1


def test_trailing_not_followed_by_needs_within():
    p = (Pattern.begin("a").where(is_type("A"))
         .not_followed_by("nb").where(is_type("B")))
    with pytest.raises(ValueError):
        p.validate()


def test_trailing_absence_concludes_at_horizon():
    p = (Pattern.begin("a").where(is_type("A"))
         .not_followed_by("nb").where(is_type("B"))
         .within(1000))
    nfa = _nfa(p)
    m, _ = feed(nfa, [(("A", 1), 0)])
    assert m == []
    matches = []
    nfa.advance_time(2000, matches)
    assert matches == [{"a": [("A", 1)]}]
    # poisoned variant: B arrives inside the window
    nfa2 = _nfa(p)
    feed(nfa2, [(("A", 1), 0), (("B", 5), 10)])
    matches = []
    nfa2.advance_time(2000, matches)
    assert matches == []


def test_within_timeout_returns_partial():
    p = (Pattern.begin("a").where(is_type("A"))
         .followed_by("b").where(is_type("B")).within(100))
    nfa = _nfa(p)
    m, t = feed(nfa, [(("A", 1), 0), (("B", 2), 500)])
    # run from A@0 timed out before B@500; B may still start a new run
    assert m == []
    assert t == [({"a": [("A", 1)]}, 0)]


def test_iterative_condition_sees_partial():
    # b must exceed every a seen so far
    p = (Pattern.begin("a").where(is_type("A")).times(2)
         .followed_by("b").where(
             lambda e, partial: e[0] == "B"
             and all(e[1] > a[1] for a in partial.get("a", []))))
    m, _ = feed(_nfa(p), [(("A", 3), 0), (("A", 7), 1), (("B", 9), 2)])
    assert len(m) == 1
    m, _ = feed(_nfa(p), [(("A", 3), 0), (("A", 7), 1), (("B", 5), 2)])
    assert m == []


def test_nfa_snapshot_restore():
    p = (Pattern.begin("a").where(is_type("A"))
         .followed_by("b").where(is_type("B")))
    nfa = _nfa(p)
    feed(nfa, [(("A", 1), 0)])
    snap = nfa.snapshot()
    nfa2 = _nfa(p)
    nfa2.restore(snap)
    m, _ = feed(nfa2, [(("B", 2), 1)])
    assert len(m) == 1


def test_no_duplicate_matches_after_nonmatching_prefix():
    """Empty stage-0 runs must not survive non-matching events — each
    match emits exactly once and per-key run state stays bounded."""
    p = (Pattern.begin("a").where(is_type("A"))
         .followed_by("b").where(is_type("B")))
    nfa = _nfa(p)
    m, _ = feed(nfa, [(("X", 0), 0), (("X", 0), 1), (("X", 0), 2),
                      (("A", 1), 3), (("B", 2), 4)])
    assert len(m) == 1
    # run state bounded: nothing left but nothing-started
    assert len(nfa.runs) <= 2


# ---------------------------------------------------------------------
# stream integration
# ---------------------------------------------------------------------

def _run_cep_job(events, pattern, keyed=True):
    env = StreamExecutionEnvironment()
    stream = env.from_collection(events, timestamped=True)
    if keyed:
        stream = stream.key_by(lambda e: e[0])
    sink = CollectSink()
    out = CEP.pattern(stream, pattern).select(
        lambda m: {k: [e for e in v] for k, v in m.items()})
    out.add_sink(sink)
    env.execute("cep-job")
    return sink.values


def test_cep_on_keyed_stream():
    # per key: login_fail x2 then success within the stream
    events = [
        (("u1", "fail"), 0), (("u2", "fail"), 5), (("u1", "fail"), 10),
        (("u1", "ok"), 20), (("u2", "ok"), 25),
    ]
    p = (Pattern.begin("f").where(lambda e: e[1] == "fail").times(2)
         .followed_by("s").where(lambda e: e[1] == "ok"))
    got = _run_cep_job(events, p)
    # only u1 had two fails before ok
    assert len(got) == 1
    assert got[0]["f"][0][0] == "u1" and len(got[0]["f"]) == 2


def test_cep_out_of_order_events_replay_in_time_order():
    events = [
        (("k", "B"), 20), (("k", "A"), 10),  # B arrives first, A earlier ts
    ]
    p = (Pattern.begin("a").where(lambda e: e[1] == "A")
         .next("b").where(lambda e: e[1] == "B"))
    got = _run_cep_job(events, p)
    assert len(got) == 1  # time-order replay: A then B


def test_cep_timeout_side_output():
    env = StreamExecutionEnvironment()
    events = [(("k", "A"), 0), (("k", "X"), 5000)]
    stream = env.from_collection(events, timestamped=True)
    stream = stream.key_by(lambda e: e[0])
    tag = OutputTag("cep-timeouts")
    p = (Pattern.begin("a").where(lambda e: e[1] == "A")
         .followed_by("b").where(lambda e: e[1] == "B").within(1000))
    ps = CEP.pattern(stream, p).with_timeout_side_output(tag)
    out = ps.select(lambda m: m)
    main_sink, to_sink = CollectSink(), CollectSink()
    out.add_sink(main_sink)
    out.get_side_output(tag).add_sink(to_sink)
    env.execute("cep-timeout")
    assert main_sink.values == []
    assert len(to_sink.values) == 1
    assert to_sink.values[0] == {"a": [("k", "A")]}


# ---------------------------------------------------------------------
# round 5: vectorized strict-chain NFA (cep/vectorized.py)
# ---------------------------------------------------------------------

def _strict_pattern(within=None):
    p = (Pattern.begin("a").where(lambda e: e[1] < 10)
         .next("b").where(lambda e: 10 <= e[1])
         .next("c").where(lambda e: e[1] >= 100))
    return p.within(within) if within else p


def _rand_events(n=8000, keys=37, seed=5):
    rng = np.random.default_rng(seed)
    return [((int(k), int(v)), t) for t, (k, v) in enumerate(
        zip(rng.integers(0, keys, n), rng.integers(0, 200, n)))]


def _run_cep(events, pattern, vectorized):
    env = StreamExecutionEnvironment()
    stream = env.from_collection(events, timestamped=True)
    stream = stream.key_by(lambda e: e[0])
    sink = CollectSink()
    ps = CEP.pattern(stream, pattern)
    if not vectorized:
        ps.disable_vectorized()
    ps.select(lambda m: tuple(tuple(e) for k in ("a", "b", "c")
                              for e in m[k])).add_sink(sink)
    env.execute("cep-vec-job")
    return sorted(sink.values)


@pytest.mark.parametrize("within", [None, 40])
def test_vectorized_equals_scalar(within):
    events = _rand_events()
    got = _run_cep(events, _strict_pattern(within), True)
    want = _run_cep(events, _strict_pattern(within), False)
    assert got == want and len(got) > 0


def test_vectorizable_gate():
    from flink_tpu.cep.vectorized import (
        pattern_strict_chain,
        pattern_vectorizable,
    )
    assert pattern_vectorizable(_strict_pattern())
    assert pattern_strict_chain(_strict_pattern())
    p = (Pattern.begin("a").where(lambda e: e[1] == 1)
         .followed_by("b").where(lambda e: e[1] == 2))
    assert pattern_vectorizable(p)           # skip-till-next admitted
    assert not pattern_strict_chain(p)       # ...on the run-list tier
    p = (Pattern.begin("a").where(lambda e: e[1] == 1)
         .followed_by_any("b").where(lambda e: e[1] == 2))
    assert not pattern_vectorizable(p)       # skip-till-ANY
    p = Pattern.begin("a").where(lambda e: e[1] == 1).times(2)
    assert not pattern_vectorizable(p)       # loop
    p = (Pattern.begin("a").where(lambda e: e[1] == 1)
         .not_next("n").where(lambda e: e[1] == 9))
    assert not pattern_vectorizable(p)       # negation
    p = (Pattern.begin("a")
         .where(lambda e, partial: e[1] == 1))
    assert not pattern_vectorizable(p)       # binary condition


def test_vectorized_scalar_condition_fallback():
    """Conditions that don't lift (data-dependent Python) keep the
    batched state machine with per-row masks — same results."""
    from flink_tpu.cep.vectorized import VectorizedStrictNFA

    def weird(e):
        # str() defeats numpy lifting
        return len(str(e[1])) == 1

    p = (Pattern.begin("a").where(weird)
         .next("b").where(lambda e: e[1] >= 100))
    eng = VectorizedStrictNFA(p)
    events = _rand_events(n=2000, keys=11, seed=9)
    keys = np.asarray([e[0][0] for e in events], np.int64)
    ts = np.asarray([t for _, t in events], np.int64)
    rows = [e for e, _ in events]
    eng.advance_batch(keys, ts, rows)
    assert eng.mode == "scalar"
    from flink_tpu.cep.nfa import NFA
    nfas = {}
    want = []
    for (k, v), t in events:
        nfa = nfas.setdefault(k, NFA(
            Pattern.begin("a").where(weird)
            .next("b").where(lambda e: e[1] >= 100)))
        ms, _ = nfa.advance((k, v), t)
        want.extend((k, tuple(m["a"][0]), tuple(m["b"][0]))
                    for m in ms)
    got = [(k, tuple(m["a"][0]), tuple(m["b"][0]))
           for k, m, _ in eng.matches]
    assert sorted(got) == sorted(want) and len(got) > 0


def test_vectorized_snapshot_restore_mid_run():
    from flink_tpu.cep.vectorized import VectorizedStrictNFA
    events = _rand_events(n=3000, keys=13, seed=3)
    keys = np.asarray([e[0][0] for e in events], np.int64)
    ts = np.asarray([t for _, t in events], np.int64)
    rows = [e for e, _ in events]
    eng = VectorizedStrictNFA(_strict_pattern(within=60))
    eng.advance_batch(keys[:1500], ts[:1500], rows[:1500])
    head = list(eng.matches)
    snap = eng.snapshot()
    eng2 = VectorizedStrictNFA(_strict_pattern(within=60))
    eng2.restore(snap)
    for e in (eng, eng2):
        e.advance_batch(keys[1500:], ts[1500:], rows[1500:])
    tail1 = eng.matches[len(head):]
    tail2 = eng2.matches
    norm = lambda ms: sorted(
        (k, tuple(tuple(x) for s in ("a", "b", "c") for x in m[s]))
        for k, m, _ in ms)
    assert norm(tail1) == norm(tail2) and len(tail2) > 0


def test_vectorized_numpy_path_differential(monkeypatch):
    """Force the pure-numpy segment-algebra path (no native lib) and
    check it against the scalar NFA — the boundary-match and
    carried-run extension code has no other coverage."""
    import flink_tpu.native as nat
    monkeypatch.setattr(nat, "available", lambda: False)
    from flink_tpu.cep.vectorized import VectorizedStrictNFA

    for within in (None, 40):
        events = _rand_events(n=6000, keys=23, seed=21)
        keys = np.asarray([e[0][0] for e in events], np.int64)
        ts = np.asarray([t for _, t in events], np.int64)
        rows = [e for e, _ in events]
        eng = VectorizedStrictNFA(_strict_pattern(within))
        for i in range(0, len(rows), 700):
            eng.advance_batch(keys[i:i+700], ts[i:i+700],
                              rows[i:i+700])
        assert eng._nat_state is None  # numpy path exercised
        got = sorted(
            (k, tuple(tuple(x) for s in ("a", "b", "c")
                      for x in m[s])) for k, m, _ in eng.matches)
        from flink_tpu.cep.nfa import NFA
        nfas = {}
        want = []
        for (k, v), t in events:
            nfa = nfas.setdefault(k, NFA(_strict_pattern(within)))
            ms, _ = nfa.advance((k, v), t)
            want.extend(
                (k, tuple(tuple(x) for s in ("a", "b", "c")
                          for x in m[s])) for m in ms)
        assert got == sorted(want) and len(got) > 0


def test_vectorized_key_type_change_raises():
    from flink_tpu.cep.vectorized import VectorizedStrictNFA
    eng = VectorizedStrictNFA(_strict_pattern())
    eng.advance_batch(np.array([1, 2], np.int64),
                      np.array([0, 1], np.int64),
                      [(1, 5), (2, 6)])
    with pytest.raises(TypeError):
        eng.advance_batch(np.array(["a", "b"]),
                          np.array([2, 3], np.int64),
                          [("a", 5), ("b", 6)])


# ---------------------------------------------------------------------
# followedBy (skip-till-next) on the vectorized run-list tier
# ---------------------------------------------------------------------

def _fb_pattern(within=None):
    p = (Pattern.begin("a").where(lambda e: e[1] < 10)
         .followed_by("b").where(lambda e: e[1] >= 180)
         .followed_by("c").where(lambda e: e[1] >= 100))
    return p.within(within) if within else p


def _batch_arrays(events):
    keys = np.asarray([e[0][0] for e in events], np.int64)
    ts = np.asarray([t for _, t in events], np.int64)
    rows = [e for e, _ in events]
    return keys, ts, rows


def test_strict_chain_compiles_to_predicate_kernel():
    """The plain-comparison strict chain must take the compiled
    bytecode path (not merely the lifted numpy path)."""
    from flink_tpu.cep.vectorized import VectorizedStrictNFA
    eng = VectorizedStrictNFA(_strict_pattern(40))
    keys, ts, rows = _batch_arrays(_rand_events(n=2000, keys=11, seed=3))
    eng.advance_batch(keys, ts, rows)
    assert eng.mode == "compiled"
    assert len(eng.matches) > 0


@pytest.mark.parametrize("within", [None, 60])
@pytest.mark.parametrize("seed", [1, 2, 7])
def test_followed_by_vectorized_equals_scalar(within, seed):
    events = _rand_events(n=6000, keys=23, seed=seed)
    got = _run_cep(events, _fb_pattern(within), True)
    want = _run_cep(events, _fb_pattern(within), False)
    assert got == want and len(got) > 0


def test_followed_by_takes_compiled_runs_tier():
    from flink_tpu.cep.vectorized import VectorizedStrictNFA
    import flink_tpu.native as nat
    if not nat.available():
        pytest.skip("native runtime unavailable")
    eng = VectorizedStrictNFA(_fb_pattern(60))
    keys, ts, rows = _batch_arrays(_rand_events(n=4000, keys=13, seed=5))
    eng.advance_batch(keys, ts, rows)
    assert eng.mode == "compiled"
    assert eng._nat_runs is not None
    assert len(eng.matches) > 0


def test_mixed_contiguity_vectorized_equals_scalar():
    """next + followedBy in one chain: strict stages clear on miss,
    skip stages carry — both inside the run-list kernel."""
    p = (Pattern.begin("a").where(lambda e: e[1] < 10)
         .followed_by("b").where(lambda e: e[1] >= 180)
         .next("c").where(lambda e: e[1] >= 100)).within(80)
    events = _rand_events(n=6000, keys=19, seed=11)
    got = _run_cep(events, p, True)
    want = _run_cep(events, p, False)
    assert got == want and len(got) > 0


def test_followed_by_scalar_mask_fallback():
    """Non-liftable condition on a followedBy stage: masks are built
    per-row in Python but the run-list kernel still advances them."""
    from flink_tpu.cep.vectorized import VectorizedStrictNFA

    def weird(e):
        return len(str(int(e[1]))) >= 3   # str defeats lift & compile

    def mk():
        return (Pattern.begin("a").where(lambda e: e[1] < 10)
                .followed_by("b").where(weird)).within(50)

    events = _rand_events(n=4000, keys=13, seed=17)
    eng = VectorizedStrictNFA(mk())
    keys, ts, rows = _batch_arrays(events)
    eng.advance_batch(keys, ts, rows)
    assert eng.mode == "scalar"
    got = sorted((k, tuple(m["a"][0]), tuple(m["b"][0]))
                 for k, m, _ in eng.matches)
    nfas, want = {}, []
    for (k, v), t in events:
        nfa = nfas.setdefault(k, NFA(mk()))
        ms, _ = nfa.advance((k, v), t)
        want.extend((k, tuple(m["a"][0]), tuple(m["b"][0])) for m in ms)
    assert got == sorted(want) and len(got) > 0


def test_followed_by_snapshot_restore_mid_run():
    """Checkpoint/restore of the extended per-key run-list state
    (ft_cep_export/ft_cep_import blob round-trip): a restored engine
    must continue identically to the uninterrupted one."""
    from flink_tpu.cep.vectorized import VectorizedStrictNFA
    events = _rand_events(n=6000, keys=13, seed=23)
    keys, ts, rows = _batch_arrays(events)
    eng = VectorizedStrictNFA(_fb_pattern(60))
    eng.advance_batch(keys[:3000], ts[:3000], rows[:3000])
    head = len(eng.matches)
    snap = eng.snapshot()
    eng2 = VectorizedStrictNFA(_fb_pattern(60))
    eng2.restore(snap)
    for e in (eng, eng2):
        e.advance_batch(keys[3000:], ts[3000:], rows[3000:])
    norm = lambda ms: sorted(
        (k, tuple(tuple(x) for s in ("a", "b", "c") for x in m[s]))
        for k, m, _ in ms)
    assert norm(eng.matches[head:]) == norm(eng2.matches)
    assert len(eng2.matches) > 0


def test_followed_by_object_keys():
    """String keys hash through the object-key path into the same
    run-list kernel."""
    events = [((f"k{k}", v), t)
              for ((k, v), t) in _rand_events(n=4000, keys=7, seed=29)]
    got = _run_cep(events, _fb_pattern(60), True)
    want = _run_cep(events, _fb_pattern(60), False)
    assert got == want and len(got) > 0


def test_native_runs_export_import_roundtrip():
    """Drive the native run-list state directly: export mid-stream,
    import into a fresh instance, and both must produce identical
    match positions on the remaining events."""
    import flink_tpu.native as nat
    if not nat.available():
        pytest.skip("native runtime unavailable")
    rng = np.random.default_rng(41)
    n, k = 20000, 4
    kh = rng.integers(1, 40, n).astype(np.uint64)
    ts = np.arange(n, dtype=np.int64)
    vals = rng.integers(0, 200, n)
    # stage masks: bit s set when event passes stage s condition
    bits = ((vals < 10).astype(np.uint32)
            | ((vals >= 150).astype(np.uint32) << 1)
            | ((vals >= 100).astype(np.uint32) << 2)
            | ((vals % 2 == 0).astype(np.uint32) << 3))
    st1 = nat.NativeCepRuns(k, within=2000)
    cut = n // 2
    refs_h, _ = st1.advance(kh[:cut], bits[:cut], ts[:cut], 0)
    blob = st1.export()
    st2 = nat.NativeCepRuns(k, within=2000)
    st2.import_(blob)
    assert st1.size() == st2.size() > 0
    r1, p1 = st1.advance(kh[cut:], bits[cut:], ts[cut:], cut)
    r2, p2 = st2.advance(kh[cut:], bits[cut:], ts[cut:], cut)
    assert np.array_equal(r1, r2) and np.array_equal(p1, p2)
    assert len(r1) > 0 and len(refs_h) > 0
