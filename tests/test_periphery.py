"""Periphery subsystems: history server, back-pressure sampling,
bucketing file sink (valid-length exactly-once), IO formats, external
sorter (ref: HistoryServer.java / BackPressureStatsTrackerImpl.java /
BucketingSink.java / api/common/io formats /
UnilateralSortMerger.java)."""

import json
import os
import time
import urllib.request

import pytest

from flink_tpu.core.formats import (
    CsvInputFormat,
    CsvOutputFormat,
    JsonRowInputFormat,
    JsonRowOutputFormat,
    TextInputFormat,
    TextOutputFormat,
)
from flink_tpu.batch.sorter import ExternalSorter, external_sorted
from flink_tpu.connectors.bucketing_sink import (
    IN_PROGRESS_SUFFIX,
    PENDING_SUFFIX,
    BucketingFileSink,
)
from flink_tpu.runtime.backpressure import classify, sample_backpressure
from flink_tpu.runtime.history import FsJobArchivist, HistoryServer


# ---------------------------------------------------------------------
# history server
# ---------------------------------------------------------------------

def test_archivist_and_history_server(tmp_path):
    d = str(tmp_path / "archive")
    FsJobArchivist.archive(d, "job-1", {"job_name": "wc",
                                        "state": "FINISHED"})
    FsJobArchivist.archive(d, "job-2", {"job_name": "agg",
                                        "state": "FAILED"})
    hs = HistoryServer([d]).start()
    try:
        base = f"http://127.0.0.1:{hs.port}"
        jobs = json.load(urllib.request.urlopen(f"{base}/jobs"))
        assert {j["job_id"] for j in jobs["jobs"]} == {"job-1", "job-2"}
        one = json.load(urllib.request.urlopen(f"{base}/jobs/job-1"))
        assert one["job_name"] == "wc" and one["state"] == "FINISHED"
        ov = json.load(urllib.request.urlopen(f"{base}/overview"))
        assert ov["jobs_finished"] == 2
        # a job archived AFTER start appears on refresh
        FsJobArchivist.archive(d, "job-3", {"job_name": "x",
                                            "state": "FINISHED"})
        hs.refresh()
        jobs = json.load(urllib.request.urlopen(f"{base}/jobs"))
        assert len(jobs["jobs"]) == 3
    finally:
        hs.stop()


def test_dispatcher_archives_to_history_dir(tmp_path):
    from flink_tpu.runtime.cluster import (
        JobManagerProcess,
        TaskManagerProcess,
    )
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment
    from flink_tpu.streaming.sources import CollectSink

    d = str(tmp_path / "archive")
    jm = JobManagerProcess(archive_dir=d)
    tm = TaskManagerProcess(jm.address, num_slots=2)
    try:
        env = StreamExecutionEnvironment()
        env.use_remote_cluster(jm.address)
        (env.from_collection(list(range(50)))
            .map(lambda v: v + 1)
            .add_sink(CollectSink()))
        env.execute("archived-job")
        deadline = time.monotonic() + 10.0
        jobs = []
        while time.monotonic() < deadline:
            jobs = FsJobArchivist.load_all(d)
            if jobs:
                break
            time.sleep(0.02)
        assert jobs and jobs[0]["job_name"] == "archived-job"
        assert jobs[0]["state"] == "FINISHED"
    finally:
        tm.stop()
        jm.stop()


# ---------------------------------------------------------------------
# back-pressure sampling
# ---------------------------------------------------------------------

def test_classify_thresholds():
    assert classify(0.0) == "ok"
    assert classify(0.3) == "low"
    assert classify(0.9) == "high"


def test_sample_backpressure_live_job():
    """A fast source into a slow sink shows high back pressure at the
    source vertex while the job runs."""
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment
    from flink_tpu.streaming.sources import (
        CollectSink,
        FromCollectionSource,
        SinkFunction,
    )

    class SlowSink(SinkFunction):
        def invoke(self, value, context=None):
            time.sleep(0.001)

    env = StreamExecutionEnvironment()
    # rebalance breaks the chain: source and sink become separate
    # vertices with a real (small) channel between them
    (env.from_collection(list(range(50_000)))
        .rebalance()
        .add_sink(SlowSink()))
    env.graph.job_name = "bp"
    executor = env._make_executor()
    executor.channel_capacity = 8
    client = executor.execute_async(env.get_job_graph())
    try:
        time.sleep(0.3)  # let the queues fill
        stats = sample_backpressure(
            client.executor_state["subtasks"], num_samples=10,
            delay_s=0.002)
        # the source/map side is backpressured by the slow sink
        assert any(s["level"] == "high" for s in stats.values()), stats
    finally:
        client.cancel()
        client.wait(30.0)


# ---------------------------------------------------------------------
# bucketing file sink
# ---------------------------------------------------------------------

def _mk_sink(base, batch_size=10**9):
    sink = BucketingFileSink(base, bucketer=lambda v: f"b{v % 2}",
                             batch_size=batch_size)
    sink.open()
    return sink


def test_bucketing_sink_lifecycle(tmp_path):
    base = str(tmp_path / "out")
    sink = _mk_sink(base)
    for v in range(10):
        sink.invoke(v)
    # snapshot: in-progress files recorded with their valid length
    snap = sink.snapshot_function_state(checkpoint_id=1)
    assert set(snap["in_progress"]) == {"b0", "b1"}
    # write post-checkpoint garbage, then crash + restore
    sink.invoke(100)
    sink.invoke(101)
    sink.close()
    sink2 = BucketingFileSink(base, bucketer=lambda v: f"b{v % 2}")
    sink2.open()
    sink2.restore_function_state(snap)
    # the truncate discarded the post-checkpoint bytes
    for bid, (path, valid) in snap["in_progress"].items():
        assert os.path.getsize(path + IN_PROGRESS_SUFFIX) == valid
    # replay the post-checkpoint records, roll, checkpoint, commit
    sink2.invoke(100)
    sink2.invoke(101)
    for bid in list(sink2._open):
        sink2._roll(bid)
    sink2.snapshot_function_state(checkpoint_id=2)
    sink2.notify_checkpoint_complete(2)
    sink2.close()
    lines = []
    for root, _d, files in os.walk(base):
        for name in files:
            assert not name.endswith(PENDING_SUFFIX)
            assert not name.endswith(IN_PROGRESS_SUFFIX)
            with open(os.path.join(root, name)) as f:
                lines.extend(f.read().split())
    assert sorted(lines, key=int) == [str(v) for v in
                                      sorted(list(range(10)) + [100, 101])]


# ---------------------------------------------------------------------
# formats
# ---------------------------------------------------------------------

def test_text_and_csv_and_json_roundtrip(tmp_path):
    t = str(tmp_path / "t.txt")
    TextOutputFormat(t).write(["a", "b"])
    assert TextInputFormat(t).read() == ["a", "b"]

    c = str(tmp_path / "t.csv")
    CsvOutputFormat(c).write([(1, "x"), (2, "y")])
    assert CsvInputFormat(c, types=[int, str]).read() == [(1, "x"), (2, "y")]

    j = str(tmp_path / "t.jsonl")
    JsonRowOutputFormat(j).write([{"a": 1}, {"b": [2, 3]}])
    assert JsonRowInputFormat(j).read() == [{"a": 1}, {"b": [2, 3]}]


# ---------------------------------------------------------------------
# external sorter
# ---------------------------------------------------------------------

def test_external_sorter_spills_and_merges():
    import random
    rng = random.Random(7)
    data = [rng.randrange(10**9) for _ in range(10_000)]
    sorter = ExternalSorter(memory_budget=1000)
    sorter.add_all(data)
    assert sorter.spill_count == 10
    out = list(sorter.sorted_iter())
    sorter.cleanup()
    assert out == sorted(data)


def test_external_sorted_descending_and_in_memory():
    data = [3, 1, 2]
    assert external_sorted(data) == [1, 2, 3]
    assert external_sorted(data, reverse=True) == [3, 2, 1]


def test_dataset_sort_partition_spills():
    from flink_tpu.batch.dataset import DataSet, ExecutionEnvironment

    env = ExecutionEnvironment()
    old = DataSet.SORT_MEMORY_BUDGET
    DataSet.SORT_MEMORY_BUDGET = 500
    try:
        import random
        rng = random.Random(1)
        data = [rng.randrange(10**6) for _ in range(5000)]
        out = (env.from_collection(data)
               .sort_partition(lambda x: x).collect())
        assert out == sorted(data)
    finally:
        DataSet.SORT_MEMORY_BUDGET = old


# ---------------------------------------------------------------------
# security (shared cluster secret on the RPC plane)
# ---------------------------------------------------------------------

def test_rpc_secret_rejects_unauthenticated():
    from flink_tpu.runtime.rpc import (
        AuthenticationException,
        RpcEndpoint,
        RpcService,
    )

    class Echo(RpcEndpoint):
        def ping(self):
            return "pong"

    server = RpcService(secret="s3cret")
    server.start_server(Echo("echo"))
    good = RpcService(secret="s3cret")
    bad = RpcService(secret=None)
    wrong = RpcService(secret="nope")
    try:
        assert good.connect(server.address, "echo").sync.ping() == "pong"
        with pytest.raises(AuthenticationException):
            bad.connect(server.address, "echo").sync.ping()
        with pytest.raises(AuthenticationException):
            wrong.connect(server.address, "echo").sync.ping()
    finally:
        for svc in (server, good, bad, wrong):
            svc.stop()


def test_secured_cluster_end_to_end():
    from flink_tpu.runtime.cluster import (
        JobManagerProcess,
        RemoteExecutor,
        TaskManagerProcess,
    )
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment
    from flink_tpu.streaming.sources import CollectSink

    jm = JobManagerProcess(secret="tok")
    tm = TaskManagerProcess(jm.address, num_slots=2, secret="tok")
    try:
        env = StreamExecutionEnvironment()
        env.use_remote_cluster(jm.address)
        env.graph.job_name = "secured"
        (env.from_collection(list(range(100)))
            .map(lambda v: v * 2)
            .add_sink(CollectSink()))
        executor = RemoteExecutor(jm.address, secret="tok")
        result = executor.execute(env.get_job_graph())
        assert sorted(result.accumulators["collected"]) == \
            [v * 2 for v in range(100)]
        # a client without the secret is refused
        from flink_tpu.runtime.rpc import AuthenticationException
        bad = RemoteExecutor(jm.address)
        with pytest.raises(AuthenticationException):
            bad.submit(env.get_job_graph())
        bad.stop()
        executor.stop()
    finally:
        tm.stop()
        jm.stop()


# ---------------------------------------------------------------------
# JDBC-shaped connector (sqlite3 driver)
# ---------------------------------------------------------------------

def test_jdbc_formats_roundtrip(tmp_path):
    import sqlite3

    from flink_tpu.connectors import JdbcInputFormat, JdbcOutputFormat

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE kv (k INTEGER PRIMARY KEY, v TEXT)")
    conn.commit()
    conn.close()

    n = JdbcOutputFormat("INSERT INTO kv VALUES (?, ?)",
                         sqlite_path=db).write([(1, "a"), (2, "b")])
    assert n == 2
    rows = JdbcInputFormat("SELECT k, v FROM kv ORDER BY k",
                           sqlite_path=db).read()
    assert rows == [(1, "a"), (2, "b")]


def test_jdbc_sink_upsert_idempotent_through_job(tmp_path):
    """Replayable source + upsert JdbcSink through a checkpointed job
    with an induced failure: replays overwrite, counts stay exact."""
    import sqlite3

    from flink_tpu.connectors import JdbcSink
    from flink_tpu.core.functions import MapFunction
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment

    db = str(tmp_path / "s.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE out (k INTEGER PRIMARY KEY, v INTEGER)")
    conn.commit()
    conn.close()

    class FailOnce(MapFunction):
        armed = True
        completed = False

        def notify_checkpoint_complete(self, cid):
            type(self).completed = True

        def map(self, value):
            cls = type(self)
            if cls.completed and cls.armed:
                cls.armed = False
                raise RuntimeError("induced")
            return value

    from flink_tpu.streaming.sources import FromCollectionSource

    class Gated(FromCollectionSource):
        HOLD = 300

        def emit_step(self, ctx, max_records):
            if FailOnce.armed and self.offset >= len(self.items) - self.HOLD:
                if self.offset >= len(self.items):
                    return False
                time.sleep(0.001)
                return super().emit_step(ctx, 1)
            return super().emit_step(ctx, max_records)

    records = [(k, k * 10) for k in range(800)]
    env = StreamExecutionEnvironment()
    env.enable_checkpointing(10)
    env.set_restart_strategy("fixed_delay", restart_attempts=3, delay_ms=0)
    (env.add_source(Gated(records), name="gated")
        .map(FailOnce(), name="failer")
        .add_sink(JdbcSink(
            "INSERT INTO out VALUES (?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v=excluded.v",
            sqlite_path=db)))
    result = env.execute("jdbc-upsert")
    assert not FailOnce.armed
    assert result.restarts == 1
    conn = sqlite3.connect(db)
    rows = conn.execute("SELECT COUNT(*), SUM(v) FROM out").fetchone()
    conn.close()
    assert rows[0] == 800
    assert rows[1] == sum(v for _, v in records)


# ---------------------------------------------------------------------
# FileSystem SPI (ref: core/fs/FileSystem.java scheme registry)
# ---------------------------------------------------------------------

def test_filesystem_spi_and_mem_scheme(tmp_path):
    from flink_tpu.core.fs import (
        LocalFileSystem,
        MemoryFileSystem,
        get_file_system,
        register_file_system,
    )

    fs, p = get_file_system(str(tmp_path / "x"))
    assert isinstance(fs, LocalFileSystem)
    fs, p = get_file_system("mem://bucket/dir/file")
    assert isinstance(fs, MemoryFileSystem)
    with fs.open("mem://a/b", "wb") as f:
        f.write(b"data")
    assert fs.exists("mem://a/b")
    with fs.open("mem://a/b") as f:
        assert f.read() == b"data"
    fs.replace("mem://a/b", "mem://a/c")
    assert fs.listdir("mem://a") == ["c"]
    fs.remove("mem://a/c")
    assert not fs.exists("mem://a/c")
    with pytest.raises(ValueError, match="no filesystem registered"):
        get_file_system("s3://nope/x")
    from flink_tpu.core import fs as fs_mod
    try:
        register_file_system("s3", MemoryFileSystem())
        fs2, _ = get_file_system("s3://now/works")
        assert isinstance(fs2, MemoryFileSystem)
    finally:
        fs_mod._REGISTRY.pop("s3", None)  # don't leak into other tests


def test_checkpoint_job_on_mem_filesystem():
    """A checkpointed job writing its checkpoints to the mem://
    scheme: the storage layer is genuinely pluggable end to end."""
    from flink_tpu.core.functions import MapFunction
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment
    from flink_tpu.streaming.sources import (
        CollectSink,
        FromCollectionSource,
    )

    class FailOnce(MapFunction):
        armed = True
        completed = False

        def notify_checkpoint_complete(self, cid):
            type(self).completed = True

        def map(self, value):
            cls = type(self)
            if cls.completed and cls.armed:
                cls.armed = False
                raise RuntimeError("induced")
            return value

    class Gated(FromCollectionSource):
        HOLD = 300

        def emit_step(self, ctx, max_records):
            if FailOnce.armed and self.offset >= len(self.items) - self.HOLD:
                if self.offset >= len(self.items):
                    return False
                time.sleep(0.001)
                return super().emit_step(ctx, 1)
            return super().emit_step(ctx, max_records)

    sink = CollectSink()
    env = StreamExecutionEnvironment()
    env.enable_checkpointing(10)
    env.set_checkpoint_storage("filesystem", "mem://ckpt/job-a")
    env.set_restart_strategy("fixed_delay", restart_attempts=3, delay_ms=0)
    (env.add_source(Gated(list(range(900))), name="gated")
        .map(FailOnce(), name="failer")
        .add_sink(sink))
    result = env.execute("mem-fs-checkpoints")
    assert not FailOnce.armed
    assert result.restarts == 1
    assert sorted(set(sink.values)) == list(range(900))
    # the checkpoints really live in the mem filesystem
    from flink_tpu.core.fs import get_file_system
    fs, _ = get_file_system("mem://ckpt/job-a")
    assert any(n.startswith("chk-") for n in fs.listdir("mem://ckpt/job-a"))


# ---------------------------------------------------------------------
# wire record codecs (the SpanningRecordSerializer role)
# ---------------------------------------------------------------------

def test_wire_codec_columnar_and_fallback():
    from flink_tpu.runtime.netchannel import decode_elements, encode_elements
    from flink_tpu.streaming.elements import (
        MAX_WATERMARK,
        StreamRecord,
        Watermark,
    )

    # homogeneous ints with timestamps -> columnar
    batch = [StreamRecord(i * 3, i * 10) for i in range(100)]
    enc = encode_elements(batch)
    assert enc[0] == "col"
    out = decode_elements(enc)
    assert [(r.value, r.timestamp) for r in out] == \
        [(r.value, r.timestamp) for r in batch]
    assert all(type(r.value) is int for r in out)

    # floats without timestamps -> columnar
    batch = [StreamRecord(i * 0.5) for i in range(10)]
    enc = encode_elements(batch)
    assert enc[0] == "col"
    assert [r.value for r in decode_elements(enc)] == \
        [r.value for r in batch]

    # tuples of primitives -> columnar (one column per field)
    batch = [StreamRecord((i, f"s{i}", i * 0.5), i) for i in range(10)]
    enc = encode_elements(batch)
    assert enc[0] == "col"
    assert decode_elements(enc) == batch

    # mixed elements (watermarks/non-record controls) -> pickle fallback
    for batch in ([StreamRecord(1, 5), Watermark(9)],
                  [MAX_WATERMARK],
                  []):
        enc = encode_elements(batch)
        assert enc[0] == "pickle"
        assert decode_elements(enc) == batch
