"""Transport security (ref: SecurityUtils.java / SSLUtils.java
internal connectivity — round-2 verdict item 10): mutual TLS on the
RPC control plane and the netchannel data plane, shared self-signed
material, plaintext refused."""

import socket
import threading

import pytest

from flink_tpu.runtime.rpc import RpcEndpoint, RpcService
from flink_tpu.runtime.tls import TlsConfig


class Echo(RpcEndpoint):
    RPC_METHODS = ("echo",)

    def __init__(self):
        super().__init__("echo")

    def echo(self, x):
        return x


@pytest.fixture(scope="module")
def tls(tmp_path_factory):
    return TlsConfig.generate_self_signed(
        str(tmp_path_factory.mktemp("tls")))


def test_tls_rpc_handshake_and_call(tls):
    server = RpcService(tls=tls)
    server.start_server(Echo())
    client = RpcService(tls=tls)
    try:
        gw = client.connect(server.address, "echo")
        assert gw.sync.echo({"n": 41}) == {"n": 41}
    finally:
        client.stop()
        server.stop()


def test_plaintext_client_refused_by_tls_server(tls):
    server = RpcService(tls=tls)
    server.start_server(Echo())
    plain = RpcService()  # no tls
    try:
        gw = plain.connect(server.address, "echo", timeout=3.0)
        with pytest.raises(Exception):
            gw.sync.echo(1)
    finally:
        plain.stop()
        server.stop()


def test_raw_socket_gets_no_data_from_tls_server(tls):
    """A plaintext peer can connect TCP but the handshake fails before
    any frame is served — the socket closes without application
    data."""
    server = RpcService(tls=tls)
    server.start_server(Echo())
    try:
        s = socket.create_connection(
            (server.host, server.port), timeout=3.0)
        s.sendall(b"\x00\x00\x00\x04junk")
        s.settimeout(3.0)
        try:
            data = s.recv(4096)
        except (TimeoutError, OSError):
            data = b""
        # either an immediate close or a TLS alert — never a frame
        assert b"result" not in data and b"payload" not in data
        s.close()
    finally:
        server.stop()


def test_wrong_certificate_refused(tls, tmp_path):
    """Mutual TLS: a client with its OWN self-signed cert (not the
    cluster's) fails verification."""
    other = TlsConfig.generate_self_signed(str(tmp_path / "other"))
    server = RpcService(tls=tls)
    server.start_server(Echo())
    intruder = RpcService(tls=other)
    try:
        with pytest.raises(Exception):
            gw = intruder.connect(server.address, "echo", timeout=3.0)
            gw.sync.echo(1)
    finally:
        intruder.stop()
        server.stop()


def test_full_job_over_tls_cluster(tls):
    """A real JM + TM + client, all three planes (RPC control, blob,
    credit data plane) under mutual TLS — the job runs end to end."""
    from flink_tpu.runtime.cluster import (
        JobManagerProcess,
        TaskManagerProcess,
    )
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment
    from flink_tpu.streaming.sources import CollectSink

    jm = JobManagerProcess(tls=tls)
    tm = TaskManagerProcess(jm.address, num_slots=4, tm_id="tls-tm",
                            tls=tls)
    try:
        env = StreamExecutionEnvironment()
        env.use_remote_cluster(jm.address, tls=tls)
        env.set_parallelism(2)  # exercises the TLS data plane exchange
        sink = CollectSink()
        (env.from_collection(list(range(2000)))
            .map(lambda x: x * 2)
            .key_by(lambda x: x % 7)
            .map(lambda x: x)
            .add_sink(sink))
        result = env.execute("tls-job")
        assert sum(result.accumulators["collected"]) == \
            sum(2 * x for x in range(2000))
    finally:
        tm.stop()
        jm.stop()


def test_tls_dir_roundtrip(tmp_path):
    """from_dir generates material once and reloads it after."""
    cfg = TlsConfig.from_dir(str(tmp_path / "d"))
    cfg2 = TlsConfig.from_dir(str(tmp_path / "d"))
    assert cfg.cert_path == cfg2.cert_path
    with open(cfg.cert_path) as f:
        assert "BEGIN CERTIFICATE" in f.read()
    ctx = cfg.server_context()
    assert ctx.verify_mode.name == "CERT_REQUIRED"
