"""Metrics time-series journal, checkpoint stats tracker, health
alerts, and the REST/HistoryServer history plane (ref: MetricStore +
CheckpointStatsTracker + the webmonitor handlers — SURVEY.md §2.2)."""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from flink_tpu.runtime.backpressure import (
    TimeAccounting,
    locate_bottleneck,
    read_backpressure_gauges,
)
from flink_tpu.runtime.history import FsJobArchivist, HistoryServer
from flink_tpu.runtime.metrics import MetricRegistry
from flink_tpu.runtime.rest import WebMonitor
from flink_tpu.runtime.timeseries import (
    HealthEvaluator,
    MetricsJournal,
    rollup,
)
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.sources import CollectSink, SourceFunction


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return json.loads(r.read().decode())


def _get_error(port, path):
    try:
        _get(port, path)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())
    raise AssertionError(f"expected HTTP error for {path}")


def _wait_for_archive(directory, timeout=15.0):
    """The archivist writes after the client unblocks — poll briefly."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.isdir(directory) and any(
                not f.endswith(".part") for f in os.listdir(directory)):
            return
        time.sleep(0.05)
    raise AssertionError(f"no archive appeared in {directory}")


# ---------------------------------------------------------------------
# journal unit tests (deterministic clocks)
# ---------------------------------------------------------------------

class _FakeClock:
    def __init__(self, start=0.0):
        self.t = start

    def __call__(self):
        return self.t


def _journal_with(samples_per_key, interval_ms=10, history_size=1024):
    """Build a journal by ingesting synthetic dumps: {key: [v, ...]}."""
    clock, wall = _FakeClock(), _FakeClock(1_000_000.0)
    j = MetricsJournal(interval_ms=interval_ms, history_size=history_size,
                       clock=clock, wall_clock=wall)
    n = max(len(v) for v in samples_per_key.values())
    for i in range(n):
        dump = {k: vs[i] for k, vs in samples_per_key.items()
                if i < len(vs)}
        j.ingest(wall.t, dump)
        clock.t += interval_ms
        wall.t += interval_ms
    return j


def test_journal_sampling_rollups_and_buckets():
    clock, wall = _FakeClock(), _FakeClock(5_000.0)
    registry = MetricRegistry()
    g = registry.job_group("j").add_group("v")
    value = {"x": 0.0}
    g.gauge("load", lambda: value["x"])
    j = MetricsJournal(registry, interval_ms=10, history_size=64,
                       clock=clock, wall_clock=wall)

    assert j.enabled
    for i in range(20):
        value["x"] = float(i)
        assert j.maybe_sample()          # exactly due every tick
        assert not j.maybe_sample()      # not due twice at one instant
        clock.t += 10
        wall.t += 10
    assert j.samples_taken == 20

    q = j.query("j.v.load")
    entry = q["series"]["j.v.load"]
    assert len(entry["samples"]) == 20
    r = entry["rollup"]
    assert r["count"] == 20 and r["min"] == 0.0 and r["max"] == 19.0
    assert r["avg"] == pytest.approx(9.5)
    assert r["p95"] == 19.0

    # since filter: drop the first half by wall-clock
    q2 = j.query("j.v.load", since_wall_ms=5_000.0 + 10 * 10)
    assert q2["series"]["j.v.load"]["rollup"]["count"] == 10

    # bucketed rollups cover the window and carry correct extrema
    q3 = j.query("j.v.load", buckets=4)
    buckets = q3["series"]["j.v.load"]["buckets"]
    assert len(buckets) == 4
    assert buckets[0]["min"] == 0.0
    assert buckets[-1]["max"] == 19.0
    total = sum(b["count"] for b in buckets)
    assert total == 20


def test_journal_ring_buffer_cap_and_payload_roundtrip():
    j = _journal_with({"a.b": list(range(50))}, history_size=16)
    assert len(j.series("a.b")) == 16          # ring buffer caps
    assert j.latest("a.b") == 49.0
    j2 = MetricsJournal.from_payload(j.to_payload())
    assert j2.series("a.b") == j.series("a.b")
    assert j2.samples_taken == j.samples_taken
    # non-numeric values never enter the journal
    j.ingest(0.0, {"s": "high", "flag": True, "none": None, "n": 1})
    assert j.keys("s") == [] and j.keys("flag") == [] and j.keys("n") == ["n"]


def test_rollup_empty_and_percentile():
    assert rollup([]) == {"count": 0}
    r = rollup(list(range(100)))
    assert r["p95"] == 95


# ---------------------------------------------------------------------
# health rules: episode semantics
# ---------------------------------------------------------------------

def test_backpressure_alert_fires_exactly_once_per_episode():
    clock, wall = _FakeClock(), _FakeClock(1_000.0)
    j = MetricsJournal(interval_ms=10, clock=clock, wall_clock=wall)
    ev = HealthEvaluator(j, bp_ratio_threshold=0.5, bp_consecutive=3,
                         wall_clock=wall)

    def feed(ratio, n):
        for _ in range(n):
            j.ingest(wall.t, {"job.1_v.backpressure.ratio": ratio})
            ev.evaluate()
            clock.t += 10
            wall.t += 10

    feed(0.2, 5)
    assert ev.alerts_total == 0
    feed(0.9, 10)                    # sustained: ONE alert, not 8
    assert ev.alerts_total == 1
    alert = ev.snapshot_alerts()[0]
    assert alert["rule"] == "backpressure-sustained"
    assert alert["metric"] == "job.1_v.backpressure.ratio"
    assert "backpressure-sustained" in ev.active_rules
    feed(0.0, 3)                     # clears -> re-arms
    assert ev.active_rules == []
    feed(0.9, 3)                     # second episode
    assert ev.alerts_total == 2


def test_watermark_lag_and_checkpoint_budget_rules():
    clock, wall = _FakeClock(), _FakeClock(0.0)
    j = MetricsJournal(interval_ms=10, clock=clock, wall_clock=wall)

    class _Stat:
        def __init__(self, d):
            self.duration_ms = d

    class _Coord:
        stats = {1: _Stat(5.0), 2: _Stat(500.0)}

    ev = HealthEvaluator(j, lag_consecutive=4,
                         checkpoint_p95_budget_ms=100.0,
                         coordinator_supplier=lambda: _Coord(),
                         wall_clock=wall)
    # strictly growing lag over 4 samples fires once
    for lag in (10, 20, 30, 40, 40, 50):
        j.ingest(wall.t, {"job.1_v.0.op-1-src.watermarkLag": lag})
        ev.evaluate()
        wall.t += 10
    rules = [a["rule"] for a in ev.snapshot_alerts()]
    assert rules.count("watermark-lag-growing") == 1
    # p95 (500 ms) over the 100 ms budget fires once despite 6 evals
    assert rules.count("checkpoint-duration-over-budget") == 1


# ---------------------------------------------------------------------
# MiniCluster end-to-end: live routes, then HistoryServer parity
# ---------------------------------------------------------------------

class _Slowish(SourceFunction):
    def __init__(self, n=3000, delay=0.001):
        self.n = n
        self.delay = delay
        self._running = True

    def run(self, ctx):
        for i in range(self.n):
            if not self._running:
                return
            ctx.collect(i)
            time.sleep(self.delay)

    def cancel(self):
        self._running = False


def test_minicluster_history_checkpoints_alerts_routes(tmp_path):
    archive = str(tmp_path / "archive")
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    env.use_mini_cluster(2)
    env.enable_checkpointing(20)
    env.config.set("metrics.sample.interval.ms", 5)
    env.config.set("metrics.history.size", 512)
    env.config.set("history.archive.dir", archive)
    sink = CollectSink()
    (env.add_source(_Slowish())
        .key_by(lambda v: v % 4)
        .map(lambda v: v + 1)
        .add_sink(sink))
    client = env.execute_async("journaled-job")
    monitor = WebMonitor(env.get_metric_registry()).start()
    live_history = live_cps = None
    try:
        monitor.track_job("journaled-job", client)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            live_history = _get(monitor.port,
                                "/jobs/journaled-job/metrics/history"
                                "?metric=*&buckets=4")
            if (live_history.get("series")
                    and not live_history.get("sampling_disabled")
                    and max(len(e["samples"]) for e in
                            live_history["series"].values()) >= 10):
                break
            time.sleep(0.05)
        assert live_history["sample_interval_ms"] == 5
        key, entry = max(live_history["series"].items(),
                         key=lambda kv: len(kv[1]["samples"]))
        assert len(entry["samples"]) >= 10
        r = entry["rollup"]
        vals = [v for _, v in entry["samples"]]
        assert r["count"] == len(vals)
        assert r["min"] == min(vals) and r["max"] == max(vals)
        assert r["avg"] == pytest.approx(sum(vals) / len(vals))
        assert sum(b["count"] for b in entry["buckets"]) == len(vals)

        # checkpoints route: per-subtask ack latencies + summary
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            live_cps = _get(monitor.port, "/jobs/journaled-job/checkpoints")
            if live_cps["summary"]["count"] >= 2:
                break
            time.sleep(0.05)
        completed = [h for h in live_cps["history"]
                     if h["status"] == "completed"]
        assert live_cps["summary"]["count"] == len(completed) >= 2
        assert completed[0]["ack_latency_ms"]  # per-subtask latencies
        for h in completed:
            assert h["duration_ms"] is not None
            assert set(h["ack_latency_ms"]) == set(
                completed[0]["ack_latency_ms"])
        assert live_cps["summary"]["duration_ms"]["count"] == len(completed)
        assert live_cps["summary"]["ack_latency_ms"]["count"] > 0

        alerts = _get(monitor.port, "/jobs/journaled-job/alerts")
        assert set(alerts) == {"alerts", "total", "rules_firing"}

        result = client.wait(timeout=60)
        assert sorted(result.accumulators["collected"]) == sorted(
            v + 1 for v in range(3000))

        # the live coordinator count and the route must agree at end
        final_cps = _get(monitor.port, "/jobs/journaled-job/checkpoints")
        assert (final_cps["counts"]["completed"]
                == result.checkpoints_completed)
    finally:
        monitor.stop()

    # ---- HistoryServer: identical route shapes post-finish ----------
    _wait_for_archive(archive)
    hs = HistoryServer([archive]).start()
    try:
        jobs = _get(hs.port, "/jobs")["jobs"]
        assert any(j["job_name"] == "journaled-job" for j in jobs)
        arch_history = _get(hs.port, "/jobs/journaled-job/metrics/history"
                                     "?metric=*&buckets=4")
        assert set(arch_history) == set(live_history)
        assert arch_history["sample_interval_ms"] == 5
        assert key in arch_history["series"]
        arch_entry = arch_history["series"][key]
        assert set(arch_entry) == set(entry)
        assert len(arch_entry["samples"]) >= 10
        arch_cps = _get(hs.port, "/jobs/journaled-job/checkpoints")
        assert set(arch_cps) == set(live_cps)
        assert (arch_cps["counts"]["completed"]
                == result.checkpoints_completed)
        arch_alerts = _get(hs.port, "/jobs/journaled-job/alerts")
        assert set(arch_alerts) == {"alerts", "total", "rules_firing"}
        arch_metrics = _get(hs.port, "/jobs/journaled-job/metrics")
        assert arch_metrics and all(k.startswith("journaled-job.")
                                    for k in arch_metrics)
    finally:
        hs.stop()


def test_local_executor_seeded_backpressure_fires_one_alert():
    """A tiny channel (capacity 8) + a slow keyed map forces the
    threaded source's emit to block on a full queue for the whole run
    — sustained backpressure on the source vertex.  The health plane
    must emit exactly ONE backpressure-sustained alert for it (episode
    semantics), not one per sample."""
    from flink_tpu.runtime.local import LocalExecutor

    env = StreamExecutionEnvironment()
    sink = CollectSink()

    def slow(v):
        # per-record time far above the emit-wait wakeup latency, so
        # the source refills the 8-slot queue between records and the
        # sampled ratio never dips mid-run; the journal ticks once per
        # loop pass (~256 map-sleeps), so n/256 passes must comfortably
        # exceed the 5-consecutive-sample alert threshold
        time.sleep(0.0005)
        return v

    (env.add_source(_Slowish(n=2500, delay=0.0))
        .key_by(lambda v: v % 2)
        .map(slow)
        .add_sink(sink))
    env.graph.job_name = "bp-job"
    executor = LocalExecutor(channel_capacity=8, sample_interval_ms=2)
    client = executor.execute_async(env.get_job_graph())
    client.wait(timeout=120)

    evaluator = client.executor_state["health"]
    journal = client.executor_state["journal"]
    assert evaluator is not None and journal.samples_taken >= 5
    bp_alerts = [a for a in evaluator.snapshot_alerts()
                 if a["rule"] == "backpressure-sustained"]
    assert len(bp_alerts) == 1, bp_alerts
    assert bp_alerts[0]["metric"].endswith(".backpressure.ratio")
    assert bp_alerts[0]["metric"].startswith("bp-job.")
    assert bp_alerts[0]["value"] > 0.5


def test_journal_disabled_by_default(tmp_path):
    env = StreamExecutionEnvironment()
    sink = CollectSink()
    env.from_collection(range(50)).map(lambda v: v).add_sink(sink)
    client = env.execute_async("nojournal-job")
    monitor = WebMonitor(env.get_metric_registry()).start()
    try:
        monitor.track_job("nojournal-job", client)
        client.wait(timeout=30)
        assert client.executor_state["journal"] is None
        assert client.executor_state["health"] is None
        body = _get(monitor.port, "/jobs/nojournal-job/metrics/history")
        assert body["sampling_disabled"] is True and body["series"] == {}
    finally:
        monitor.stop()


# ---------------------------------------------------------------------
# time attribution: busy+idle+backPressured tiles wall time
# ---------------------------------------------------------------------

def test_time_accounting_tiles_elapsed_time():
    """Deterministic clock: every observed interval lands in exactly
    one bucket, so the windowed rates sum to exactly 1000 ms/s."""
    acct = TimeAccounting()
    ms = 1_000_000  # ns
    t = 0
    acct.observe(False, False, now_ns=t)
    for _ in range(100):                      # 100 ms busy
        t += ms
        acct.observe(True, False, now_ns=t)
    for _ in range(60):                       # 60 ms idle
        t += ms
        acct.observe(False, False, now_ns=t)
    for _ in range(40):                       # 40 ms backpressured
        t += ms
        acct.observe(False, True, now_ns=t)
    busy, idle, bp = acct.rates()
    assert busy == pytest.approx(500.0)
    assert idle == pytest.approx(300.0)
    assert bp == pytest.approx(200.0)
    assert busy + idle + bp == pytest.approx(1000.0)


def _attribution_rates(dump, job_name):
    """{<vid>_<vname>.<i>: [busy, idle, backPressured]} from a dump."""
    out = {}
    suffixes = (".busyTimeMsPerSecond", ".idleTimeMsPerSecond",
                ".backPressuredTimeMsPerSecond")
    for k, v in dump.items():
        if not k.startswith(job_name + "."):
            continue
        for i, suffix in enumerate(suffixes):
            if k.endswith(suffix):
                key = k[len(job_name) + 1:-len(suffix)]
                out.setdefault(key, [0.0, 0.0, 0.0])[i] = float(v)
    return out


def _poll_attribution(registry, job_name, require=None, timeout=60.0):
    """Poll until every subtask with a completed attribution window
    tiles to 1000 ms/s (±10%) AND the scenario predicate holds.
    Subtasks still inside their first window read (0, 0, 0) and are
    excluded; three separate gauge reads can straddle a window swap,
    so a torn read retries instead of failing."""
    deadline = time.monotonic() + timeout
    last = {}
    while time.monotonic() < deadline:
        rates = _attribution_rates(registry.dump(), job_name)
        live = {k: tuple(v) for k, v in rates.items() if sum(v) > 0.0}
        last = live
        if (live
                and all(abs(sum(v) - 1000.0) <= 100.0
                        for v in live.values())
                and (require is None or require(live))):
            return live
        time.sleep(0.05)
    raise AssertionError(
        f"attribution invariant/predicate never held for {job_name}: "
        f"{last}")


def test_attribution_invariant_idle_job():
    """A trickle source leaves the downstream keyed map waiting on
    empty input most of each second: idle dominates, and the three
    gauges still tile to ~1000 ms/s."""
    env = StreamExecutionEnvironment()
    sink = CollectSink()
    (env.add_source(_Slowish(n=600, delay=0.005))
        .key_by(lambda v: v % 2)
        .map(lambda v: v)
        .add_sink(sink))
    client = env.execute_async("idle-attr-job")
    try:
        _poll_attribution(
            env.get_metric_registry(), "idle-attr-job",
            require=lambda live: any(v[1] > 500.0 for v in live.values()))
    finally:
        client.wait(timeout=60)


def test_attribution_invariant_saturated_job():
    """A map that sleeps per record keeps its subtasks working the
    whole pass: busy dominates on the map vertex."""
    env = StreamExecutionEnvironment()
    sink = CollectSink()

    def heavy(v):
        time.sleep(0.0005)
        return v

    (env.add_source(_Slowish(n=3000, delay=0.0))
        .key_by(lambda v: v % 2)
        .map(heavy)
        .add_sink(sink))
    client = env.execute_async("busy-attr-job")
    try:
        _poll_attribution(
            env.get_metric_registry(), "busy-attr-job",
            require=lambda live: any(v[0] > 500.0 for v in live.values()))
    finally:
        client.wait(timeout=120)


def test_attribution_invariant_seeded_backpressure_job():
    """The PR-6 seeded-backpressure fixture (8-slot channel + slow
    keyed map): the blocked source reads backPressured, the slow map
    busy, and both tile to ~1000 ms/s."""
    from flink_tpu.runtime.local import LocalExecutor

    env = StreamExecutionEnvironment()
    sink = CollectSink()

    def slow(v):
        time.sleep(0.0005)
        return v

    (env.add_source(_Slowish(n=2500, delay=0.0))
        .key_by(lambda v: v % 2)
        .map(slow)
        .add_sink(sink))
    env.graph.job_name = "bp-attr-job"
    executor = LocalExecutor(channel_capacity=8)
    client = executor.execute_async(env.get_job_graph())
    try:
        _poll_attribution(
            executor.metrics, "bp-attr-job",
            require=lambda live: (
                any(v[2] > 500.0 for v in live.values())
                and any(v[0] > 500.0 for v in live.values())))
    finally:
        client.wait(timeout=120)


# ---------------------------------------------------------------------
# bottleneck localization
# ---------------------------------------------------------------------

def test_locate_bottleneck_picks_most_downstream_saturated_vertex():
    # chain 1 -> 2 -> 3 -> 4: vertex 3 is the deepest busy-saturated
    # vertex with a backpressured upstream — 1 and 2 are victims of
    # the propagating pressure, 4 is merely starved
    upstreams = {1: [], 2: [1], 3: [2], 4: [3]}
    stats = {
        1: {"vertex_id": 1, "name": "src", "busy_ms_per_s": 100.0,
            "backpressure_ratio": 1.0},
        2: {"vertex_id": 2, "name": "mid", "busy_ms_per_s": 900.0,
            "backpressure_ratio": 0.8},
        3: {"vertex_id": 3, "name": "slow", "busy_ms_per_s": 950.0,
            "backpressure_ratio": 0.0},
        4: {"vertex_id": 4, "name": "sink", "busy_ms_per_s": 50.0,
            "backpressure_ratio": 0.0},
    }
    b = locate_bottleneck(upstreams, stats)
    assert b["vertex_id"] == 3 and b["name"] == "slow"
    assert [u["vertex_id"] for u in b["backpressured_upstreams"]] == [2]
    assert b["busyMsPerSecond"] == 950.0
    # no stats / raised thresholds -> no bottleneck, never a crash
    assert locate_bottleneck(upstreams, {}) is None
    assert locate_bottleneck(upstreams, stats,
                             busy_threshold=2000.0) is None
    # raising the ratio bar disqualifies 3 (upstream 2 at 0.8) but 2
    # still qualifies through src at 1.0 — localization moves upstream
    assert locate_bottleneck(upstreams, stats,
                             ratio_threshold=0.9)["vertex_id"] == 2


def test_read_backpressure_gauges_from_dump():
    dump = {"j.1_src.backpressure.ratio": 0.75,
            "j.1_src.backpressure.level": "high",
            "j.2_map.backpressure.ratio": 0.0,
            "other.1_x.backpressure.ratio": 1.0}
    out = read_backpressure_gauges(dump, "j")
    assert set(out) == {1, 2}
    assert out[1]["max_ratio"] == 0.75 and out[1]["level"] == "high"
    assert out[2]["level"] == "ok"


def test_live_bottleneck_names_the_slowed_vertex():
    """Acceptance: under seeded backpressure the REST route names the
    artificially-slowed vertex exactly, and the bottleneck-stable
    health rule fires once for the episode."""
    from flink_tpu.runtime.local import LocalExecutor

    env = StreamExecutionEnvironment()
    sink = CollectSink()

    def slow(v):
        time.sleep(0.0005)
        return v

    (env.add_source(_Slowish(n=2500, delay=0.0))
        .key_by(lambda v: v % 2)
        .map(slow, name="slow-map")
        .add_sink(sink))
    env.graph.job_name = "bn-job"
    graph = env.get_job_graph()
    expected = [vid for vid, v in graph.vertices.items()
                if "slow-map" in v.name]
    assert len(expected) == 1, graph.vertices
    executor = LocalExecutor(channel_capacity=8, sample_interval_ms=2)
    client = executor.execute_async(graph)
    monitor = WebMonitor(executor.metrics).start()
    try:
        monitor.track_job("bn-job", client)
        located = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            located = _get(monitor.port,
                           "/jobs/bn-job/bottleneck")["bottleneck"]
            if located is not None:
                break
            time.sleep(0.05)
        assert located is not None, "bottleneck never located"
        assert located["vertex_id"] == expected[0]
        assert "slow-map" in located["name"]
        assert located["backpressured_upstreams"]
        assert located["busyMsPerSecond"] > 500.0
        # raised thresholds clear it (param plumbing end to end)
        body = _get(monitor.port,
                    "/jobs/bn-job/bottleneck?busy_threshold=2000")
        assert body["bottleneck"] is None
        assert body["busy_threshold_ms_per_s"] == 2000.0
        client.wait(timeout=120)
        evaluator = client.executor_state["health"]
        stable = [a for a in evaluator.snapshot_alerts()
                  if a["rule"] == "bottleneck-stable"]
        assert len(stable) == 1, stable
    finally:
        monitor.stop()


def test_history_server_bottleneck_replay_from_archive(tmp_path):
    """`/bottleneck` replays localization over the archived metrics
    snapshot + upstream map (JSON round-trips the vertex-id keys to
    strings; the route converts them back)."""
    metrics = {
        "done-job.1_src.backpressure.ratio": 1.0,
        "done-job.1_src.0.busyTimeMsPerSecond": 100.0,
        "done-job.2_slowmap.backpressure.ratio": 0.0,
        "done-job.2_slowmap.0.busyTimeMsPerSecond": 980.0,
    }
    FsJobArchivist.archive(str(tmp_path), "job-2", {
        "job_name": "done-job", "state": "FINISHED",
        "metrics": metrics, "upstreams": {"1": [], "2": [1]}})
    hs = HistoryServer([str(tmp_path)]).start()
    try:
        body = _get(hs.port, "/jobs/done-job/bottleneck")
        b = body["bottleneck"]
        assert b["vertex_id"] == 2 and b["name"] == "slowmap"
        assert b["backpressured_upstreams"][0]["vertex_id"] == 1
        body = _get(hs.port,
                    "/jobs/done-job/bottleneck?busy_threshold=2000")
        assert body["bottleneck"] is None
    finally:
        hs.stop()


# ---------------------------------------------------------------------
# REST error paths: 404 JSON bodies + 400 on malformed params
# ---------------------------------------------------------------------

def test_rest_error_paths_on_live_monitor():
    monitor = WebMonitor(MetricRegistry()).start()

    class _Client:
        executor_state = {"journal": None, "health": None,
                          "coordinator": None}
        done = False

    try:
        monitor.track_job("real-job", _Client())
        for sub in ("", "/metrics", "/metrics/history", "/checkpoints",
                    "/alerts", "/backpressure", "/detail", "/exceptions",
                    "/traces", "/traces?scope=cluster", "/bottleneck"):
            code, body = _get_error(monitor.port, f"/jobs/nope{sub}")
            assert code == 404, f"/jobs/nope{sub} -> {code}"
            assert "error" in body and "not found" in body["error"]
        for q in ("since=abc", "buckets=zero", "buckets=-3", "metric="):
            code, body = _get_error(
                monitor.port, f"/jobs/real-job/metrics/history?{q}")
            assert code == 400, f"?{q} -> {code}"
            assert "error" in body
        for path in ("/jobs/real-job/traces?scope=bogus",
                     "/jobs/real-job/bottleneck?busy_threshold=abc",
                     "/jobs/real-job/bottleneck?ratio_threshold=much"):
            code, body = _get_error(monitor.port, path)
            assert code == 400, f"{path} -> {code}"
            assert "error" in body
        # a tracked job with no metrics: null bottleneck, not an error
        body = _get(monitor.port, "/jobs/real-job/bottleneck")
        assert body["bottleneck"] is None
        assert body["busy_threshold_ms_per_s"] == 500.0
        assert body["ratio_threshold"] == 0.5
    finally:
        monitor.stop()


def test_rest_error_paths_on_history_server(tmp_path):
    archive = str(tmp_path)
    FsJobArchivist.archive(archive, "job-1", {
        "job_name": "done-job", "state": "FINISHED", "restarts": 0,
        "checkpoints_completed": 0})
    hs = HistoryServer([archive]).start()
    try:
        for sub in ("", "/metrics", "/metrics/history", "/checkpoints",
                    "/alerts", "/traces", "/traces?scope=cluster",
                    "/exceptions", "/bottleneck"):
            code, body = _get_error(hs.port, f"/jobs/nope{sub}")
            assert code == 404 and "error" in body
        code, body = _get_error(
            hs.port, "/jobs/done-job/metrics/history?since=abc")
        assert code == 400 and "error" in body
        for path in ("/jobs/done-job/traces?scope=bogus",
                     "/jobs/done-job/bottleneck?busy_threshold=abc",
                     "/jobs/done-job/bottleneck?ratio_threshold=much"):
            code, body = _get_error(hs.port, path)
            assert code == 400, f"{path} -> {code}"
            assert "error" in body
        # archived without a cluster bundle: empty merged trace shape
        body = _get(hs.port, "/jobs/done-job/traces?scope=cluster")
        assert body == {"enabled": False, "scope": "cluster",
                        "trace": {"traceEvents": []}}
        # archived without metrics/upstreams: null bottleneck
        assert _get(hs.port,
                    "/jobs/done-job/bottleneck")["bottleneck"] is None
        # archived-but-never-sampled job serves the disabled shape
        body = _get(hs.port, "/jobs/done-job/metrics/history")
        assert body["sampling_disabled"] is True
        # lookup works by job_id AND job_name (live-route parity)
        assert _get(hs.port, "/jobs/job-1")["state"] == "FINISHED"
        assert _get(hs.port, "/jobs/done-job")["state"] == "FINISHED"
    finally:
        hs.stop()


# ---------------------------------------------------------------------
# cluster mode: workers ship samples to the JobMaster over RPC
# ---------------------------------------------------------------------

def test_cluster_metrics_shipping_and_archive(tmp_path):
    from flink_tpu.runtime.cluster import (
        JobManagerProcess,
        TaskManagerProcess,
    )
    archive = str(tmp_path / "archive")
    jm = JobManagerProcess(archive_dir=archive)
    tms = [TaskManagerProcess(jm_address=jm.address, num_slots=2)
           for _ in range(2)]
    try:
        env = StreamExecutionEnvironment()
        env.set_parallelism(2)
        env.config.set("metrics.sample.interval.ms", 10)
        env.use_remote_cluster(jm.address)
        (env.from_collection(range(20000))
            .key_by(lambda v: v % 4)
            .map(lambda v: v * 2)
            .add_sink(CollectSink()))
        env.execute("cluster-journal-job")

        _wait_for_archive(archive)
        hs = HistoryServer([archive]).start()
        try:
            jobs = _get(hs.port, "/jobs")["jobs"]
            assert any(j["job_name"] == "cluster-journal-job"
                       for j in jobs)
            body = _get(hs.port,
                        "/jobs/cluster-journal-job/metrics/history")
            assert not body.get("sampling_disabled")
            assert body["series"], "workers should have shipped samples"
            assert body["sample_interval_ms"] == 10
            # the shipped dumps also land as the final metrics snapshot
            dump = _get(hs.port, "/jobs/cluster-journal-job/metrics")
            assert dump
        finally:
            hs.stop()
    finally:
        for tm in tms:
            tm.stop()
        jm.stop()


def test_cluster_trace_shipping_and_merged_archive(tmp_path):
    """With tracing on, workers ship tracer ring buffers alongside the
    report_metrics cadence; the Dispatcher archives the raw buffers +
    ping-burst clock offsets, and the HistoryServer replays ONE merged
    cluster trace with spans from both workers, clock-aligned and
    normalized to t=0."""
    from flink_tpu.runtime.cluster import (
        JobManagerProcess,
        TaskManagerProcess,
    )
    from flink_tpu.runtime.tracing import get_tracer

    archive = str(tmp_path / "archive")
    jm = JobManagerProcess(archive_dir=archive)
    tms = [TaskManagerProcess(jm_address=jm.address, num_slots=2)
           for _ in range(2)]
    tracer = get_tracer()
    tracer.enabled = True
    try:
        env = StreamExecutionEnvironment()
        env.set_parallelism(2)
        env.config.set("metrics.sample.interval.ms", 10)
        env.use_remote_cluster(jm.address)
        (env.from_collection(range(20000))
            .key_by(lambda v: v % 4)
            .map(lambda v: v * 2)
            .add_sink(CollectSink()))
        env.execute("cluster-trace-job")

        _wait_for_archive(archive)
        hs = HistoryServer([archive]).start()
        try:
            body = _get(hs.port,
                        "/jobs/cluster-trace-job/traces?scope=cluster")
            assert body["enabled"] is True and body["scope"] == "cluster"
            trace = body["trace"]
            lanes = trace["metadata"]["lanes"]
            worker_lanes = [l for l in lanes if l.startswith("tm-")]
            assert len(worker_lanes) >= 2, lanes
            spans = [e for e in trace["traceEvents"] if e["ph"] != "M"]
            assert spans
            ts = [e["ts"] for e in spans]
            assert ts == sorted(ts) and ts[0] == 0.0
            assert len({e["pid"] for e in spans}) >= 2
        finally:
            hs.stop()
    finally:
        tracer.enabled = False
        tracer.reset()
        for tm in tms:
            tm.stop()
        jm.stop()


# ---------------------------------------------------------------------
# CLI: flink_tpu top
# ---------------------------------------------------------------------

def test_cli_top_once(capsys):
    from flink_tpu.cli import main as cli_main
    env = StreamExecutionEnvironment()
    env.enable_checkpointing(20)
    sink = CollectSink()
    env.add_source(_Slowish(n=4000, delay=0.0005)) \
       .map(lambda v: v + 1).add_sink(sink)
    client = env.execute_async("topped-job")
    monitor = WebMonitor(env.get_metric_registry()).start()
    try:
        monitor.track_job("topped-job", client)
        time.sleep(0.4)
        rc = cli_main(["top", f"http://127.0.0.1:{monitor.port}",
                       "--once"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "topped-job" in out and "RUNNING" in out
        assert "rec/s" in out and "backpressure" in out
        assert "checkpoints:" in out and "alerts:" in out
        assert "BOTTLENECK" in out  # column header + footer line
    finally:
        client.cancel()
        client.wait(timeout=30)
        monitor.stop()
    # unreachable endpoint: clean error, not a traceback
    assert cli_main(["top", "http://127.0.0.1:1", "--once"]) == 1


# ---------------------------------------------------------------------
# latency histograms: journal series + `flink_tpu top` footer
# ---------------------------------------------------------------------

def test_latency_percentiles_reach_journal():
    """`latency.*` histogram percentiles flatten into the journal like
    any other dict-valued metric — the end-to-end latency picture
    survives into `/metrics/history` and the archive."""
    env = StreamExecutionEnvironment()
    env.set_latency_tracking_interval(0)  # every executor loop pass
    env.config.set("metrics.sample.interval.ms", 2)
    (env.add_source(_Slowish(n=3000, delay=0.0))
        .key_by(lambda v: v % 2)  # marker crosses an edge
        .map(lambda v: v + 1)
        .add_sink(CollectSink()))
    client = env.execute_async("lat-journal-job")
    client.wait(timeout=120)

    journal = client.executor_state["journal"]
    p99_keys = journal.keys("lat-journal-job.latency.*.p99")
    assert p99_keys, journal.keys("*")[:20]
    assert all(".latency.source_" in k for k in p99_keys)
    for k in p99_keys:
        assert journal.latest(k) >= 0.0
    # the full percentile set flattens alongside
    base = p99_keys[0][:-len(".p99")]
    for q in ("p50", "p95", "count"):
        assert journal.latest(f"{base}.{q}") is not None


def test_top_latency_footer_picks_worst_subtask():
    from flink_tpu.cli import _top_latency_footer
    metrics = {
        "j.latency.source_src_0.operator_op": {
            "count": 5, "p50": 1.0, "p95": 2.0, "p99": 3.0},
        "j.latency.source_src_1.operator_op": {
            "count": 5, "p50": 2.5, "p95": 1.0, "p99": 2.0},
        # empty histogram: no markers seen yet -> skipped
        "j.latency.source_src_0.operator_other": {"count": 0},
        "j.numRecordsOut": 7,
    }
    line = _top_latency_footer("j", metrics)
    # per-quantile max across subtasks of the same source operator
    assert line == "latency ms (p50/p95/p99): src→op 2.5/2.0/3.0"
    assert _top_latency_footer("j", {"j.numRecordsOut": 7}) == ""


def test_top_hot_frames_and_hot_column_render():
    from flink_tpu.cli import _top_hot_frames, _top_render
    from flink_tpu.runtime.profiler import (
        ON_CPU,
        flamegraph_payload,
        get_profiler,
    )
    p = get_profiler()
    p.reset()
    try:
        p.ingest("j", "1_map", 0, ["a.py:f", "b.py:g"], ON_CPU)
        p.ingest("j", "1_map", 0, ["a.py:f", "b.py:g"], ON_CPU)
        flame = flamegraph_payload(p.export(job="j"), "j")
    finally:
        p.reset()
    hot = _top_hot_frames(flame)
    assert hot == {1: "b.py:g"}
    assert _top_hot_frames(None) == {}
    rows = [{"id": 1, "name": "map", "parallelism": 2,
             "records_per_s": 10.0, "bp_ratio": None, "bp_level": None,
             "watermark_lag_ms": None, "columnar_ratio": None,
             "columnar_boxed": None, "hot": hot.get(1)}]
    out = _top_render("j", "RUNNING", rows, {}, {},
                      latency_line="latency ms (p50/p95/p99): s→o "
                                   "1.0/2.0/3.0")
    assert "HOT" in out
    assert "b.py:g" in out
    assert "latency ms (p50/p95/p99)" in out
