"""Link micro-probe: decision logic + auto-tier wiring.

The probe's measurement path runs against whatever backend the test
process has (CPU under conftest), so the decision logic is tested by
seeding the module cache — the threshold comparison must not depend on
a live accelerator."""

import numpy as np

from flink_tpu.ops import link_probe
from flink_tpu.ops.sketches import HyperLogLogAggregate
from flink_tpu.streaming.log_windows import LogStructuredTumblingWindows


def _seeded(cache):
    old = dict(link_probe._cache)
    link_probe._cache.clear()
    link_probe._cache.update(cache)
    return old


def _restore(old):
    link_probe._cache.clear()
    link_probe._cache.update(old)


def test_tier_decision_thresholds():
    old = _seeded({"h2d_gbps": 0.6, "cpu": 0.0})
    try:
        assert link_probe.recommended_finish_tier() == "host"
        _seeded({"h2d_gbps": 16.0, "cpu": 0.0})
        assert link_probe.recommended_finish_tier() == "device"
        _seeded({"h2d_gbps": float("inf"), "cpu": 1.0})
        # same memory domain: the C++ finish IS the device
        assert link_probe.recommended_finish_tier() == "host"
    finally:
        _restore(old)


def test_explicit_override_passes_through():
    old = _seeded({"h2d_gbps": 0.01, "cpu": 0.0})
    try:
        assert link_probe.recommended_finish_tier("device") == "device"
        assert link_probe.recommended_finish_tier("host") == "host"
    finally:
        _restore(old)


def test_auto_engine_resolves_via_probe():
    """finish_tier="auto" must land on the probe's recommendation at
    construction time (not stay "auto")."""
    old = _seeded({"h2d_gbps": 16.0, "cpu": 0.0})
    try:
        eng = LogStructuredTumblingWindows(
            HyperLogLogAggregate(precision=10), 1000, finish_tier="auto")
        assert eng.mode.finish_tier == "device"
        _seeded({"h2d_gbps": 0.5, "cpu": 0.0})
        eng = LogStructuredTumblingWindows(
            HyperLogLogAggregate(precision=10), 1000, finish_tier="auto")
        assert eng.mode.finish_tier == "host"
    finally:
        _restore(old)


def test_measure_runs_on_this_backend():
    """The real measurement path (CPU backend under conftest) returns
    a finite decision without compiling device code."""
    m = link_probe.measure(force=True)
    assert set(m) == {"h2d_gbps", "cpu"}
    assert link_probe.recommended_finish_tier() in ("host", "device")


def test_device_finish_matches_host_finish():
    """Both finishes implement one semantics: same keys, estimates
    within float-summation-order tolerance (the device scan sums the
    2^-rank contributions in f32 cumsum order, the host in run
    order)."""
    rng = np.random.default_rng(5)
    n = 20_000
    keys = rng.integers(0, 500, n).astype(np.uint64)
    ts = np.sort(rng.integers(0, 1000, n).astype(np.int64))
    vals = rng.integers(0, 5000, n).astype(np.uint64)
    agg = HyperLogLogAggregate(precision=11)
    outs = {}
    for tier in ("host", "device"):
        eng = LogStructuredTumblingWindows(agg, 1000, finish_tier=tier)
        eng.emit_arrays = True
        eng.process_batch(keys, ts, values=vals)
        eng.advance_watermark(1999)
        k, r, _, _ = eng.fired[0]
        outs[tier] = dict(zip(k.tolist(), r.tolist()))
    assert set(outs["host"]) == set(outs["device"])
    for k, v in outs["host"].items():
        assert abs(v - outs["device"][k]) <= 1e-3 * max(v, 1.0), \
            (k, v, outs["device"][k])
