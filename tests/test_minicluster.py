"""MiniCluster multi-worker execution + mesh-sharded window path.

The multi-worker tier of the test pyramid (ref:
flink-runtime/.../minicluster/MiniCluster.java and the ITCase bases in
flink-test-utils-parent — SURVEY.md §4.4): real worker threads, real
cross-worker channel traffic, checkpointing and failure recovery, plus
the mesh-sharded device window engine driven from a JobGraph over the
8-device virtual CPU mesh.
"""

import collections

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from flink_tpu.core.functions import AggregateFunction, MapFunction
from flink_tpu.ops.device_agg import CountAggregate, SumAggregate
from flink_tpu.parallel.mesh_windows import (
    MeshTumblingWindows,
    MeshWindowOverflowError,
)
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.sources import (
    BoundedOutOfOrdernessTimestampExtractor,
    CollectSink,
    FromCollectionSource,
)
from flink_tpu.streaming.windowing import Time, TumblingEventTimeWindows


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must force 8 virtual devices"
    return Mesh(np.array(devs[:8]), ("kg",))


# ---------------------------------------------------------------------
# MeshTumblingWindows engine semantics
# ---------------------------------------------------------------------

def test_mesh_engine_multi_window_counts(mesh):
    eng = MeshTumblingWindows(CountAggregate(), 1000, mesh,
                              capacity_per_window_shard=256, step_batch=64)
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 50, 500)
    ts = rng.integers(0, 3000, 500)
    eng.process_batch(keys, ts)
    eng.advance_watermark(999)
    eng.advance_watermark(2999)
    expect = collections.Counter()
    for k, t in zip(keys.tolist(), ts.tolist()):
        expect[(k, t - t % 1000)] += 1
    got = {(k, s): v for (k, v, s, e) in eng.emitted}
    assert got == dict(expect)
    # window ends are start + size
    assert all(e == s + 1000 for (_, _, s, e) in eng.emitted)


def test_mesh_engine_sums_match_host(mesh):
    eng = MeshTumblingWindows(SumAggregate(), 500, mesh,
                              capacity_per_window_shard=256, step_batch=64)
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 30, 400)
    ts = rng.integers(0, 2000, 400)
    vals = rng.random(400).astype(np.float32)
    eng.process_batch(keys, ts, vals)
    eng.advance_watermark(1999)
    expect = collections.defaultdict(float)
    for k, t, v in zip(keys.tolist(), ts.tolist(), vals.tolist()):
        expect[(k, t - t % 500)] += v
    got = {(k, s): v for (k, v, s, e) in eng.emitted}
    assert set(got) == set(expect)
    for ks in expect:
        assert abs(got[ks] - expect[ks]) < 1e-3


def test_mesh_engine_drops_late_records(mesh):
    eng = MeshTumblingWindows(CountAggregate(), 1000, mesh,
                              capacity_per_window_shard=64, step_batch=64)
    eng.process_batch(np.array([1, 2]), np.array([100, 1100]))
    eng.advance_watermark(999)       # fires window 0
    eng.process_batch(np.array([3]), np.array([500]))  # late for window 0
    assert eng.num_late_dropped == 1
    eng.advance_watermark(1999)
    got = {(k, s) for (k, v, s, e) in eng.emitted}
    assert got == {(1, 0), (2, 1000)}


def test_mesh_engine_far_future_parks_and_ingests(mesh):
    # ring=2: a record 2+ windows ahead of a live one parks host-side
    eng = MeshTumblingWindows(CountAggregate(), 1000, mesh, ring=2,
                              capacity_per_window_shard=64, step_batch=64)
    eng.process_batch(np.array([1]), np.array([100]))     # window 0 (ring 0)
    eng.process_batch(np.array([2]), np.array([2100]))    # window 2000 → ring 0 busy
    assert eng.pending, "far-future record should park"
    eng.advance_watermark(999)   # window 0 fires, ring 0 frees, pending ingests
    eng.advance_watermark(2999)
    got = {(k, s) for (k, v, s, e) in eng.emitted}
    assert got == {(1, 0), (2, 2000)}


def test_mesh_engine_parked_window_fires_on_big_watermark_jump(mesh):
    """A parked window whose due-time passes while parked must still
    fire (one watermark jump past everything — the end-of-input
    MAX_WATERMARK shape), not be counted late: its records arrived on
    time."""
    eng = MeshTumblingWindows(CountAggregate(), 1000, mesh, ring=2,
                              capacity_per_window_shard=64, step_batch=64)
    eng.process_batch(np.array([1]), np.array([100]))   # window 0, ring 0
    eng.process_batch(np.array([2]), np.array([2100]))  # window 2000 parks
    assert eng.pending
    eng.advance_watermark(2 ** 62)  # everything due at once
    got = {(k, s) for (k, v, s, e) in eng.emitted}
    assert got == {(1, 0), (2, 2000)}
    assert eng.num_late_dropped == 0
    assert not eng.pending and not eng.live
    # per-window key directories are cleaned up after fires
    assert not eng.key_directory


def test_mesh_engine_overflow_raises(mesh):
    eng = MeshTumblingWindows(CountAggregate(), 1000, mesh,
                              capacity_per_window_shard=2, step_batch=64,
                              max_probes=2)
    keys = np.arange(1000)
    ts = np.full(1000, 10)
    with pytest.raises(MeshWindowOverflowError):
        eng.process_batch(keys, ts)
        eng.flush()


def test_mesh_engine_snapshot_restore_midwindow(mesh):
    rng = np.random.default_rng(5)
    keys = rng.integers(0, 40, 300)
    ts = rng.integers(0, 2000, 300)

    eng = MeshTumblingWindows(CountAggregate(), 1000, mesh,
                              capacity_per_window_shard=256, step_batch=64)
    eng.process_batch(keys[:150], ts[:150])
    snap = eng.snapshot()

    eng2 = MeshTumblingWindows(CountAggregate(), 1000, mesh,
                               capacity_per_window_shard=256, step_batch=64)
    eng2.restore(snap)
    eng2.process_batch(keys[150:], ts[150:])
    eng2.advance_watermark(1999)

    expect = collections.Counter()
    for k, t in zip(keys.tolist(), ts.tolist()):
        expect[(k, t - t % 1000)] += 1
    got = {(k, s): v for (k, v, s, e) in eng2.emitted}
    assert got == dict(expect)


# ---------------------------------------------------------------------
# MiniCluster execution
# ---------------------------------------------------------------------

class SumAgg(AggregateFunction):
    def create_accumulator(self):
        return 0

    def add(self, value, acc):
        return acc + value[1]

    def get_result(self, acc):
        return acc

    def merge(self, a, b):
        return a + b


def _records(n_keys=8, per_key=100):
    records = []
    for i in range(per_key):
        for k in range(n_keys):
            records.append(((f"k{k}", 1), i * 10))
    return records


@pytest.mark.parametrize("n_tms", [1, 3])
def test_minicluster_windowed_sum(n_tms):
    records = _records()
    sink = CollectSink()
    env = StreamExecutionEnvironment()
    env.use_mini_cluster(n_tms)
    env.set_parallelism(2)
    (env.from_collection(records, timestamped=True)
        .key_by(lambda v: v[0])
        .time_window(Time.milliseconds_of(500))
        .aggregate(SumAgg())
        .add_sink(sink))
    env.execute("mini-windowed-sum")
    assert sum(sink.values) == len(records)


def test_minicluster_map_parallelism_spread():
    """Subtasks of a parallel map land on different workers and all
    records arrive exactly once."""
    env = StreamExecutionEnvironment()
    env.use_mini_cluster(2)
    sink = CollectSink()
    (env.from_collection(list(range(1000)))
        .rebalance()
        .map(lambda v: v * 2, name="double")
        .add_sink(sink))
    env.execute("mini-map")
    assert sorted(sink.values) == [v * 2 for v in range(1000)]


class FailOnceAfterCheckpoint(MapFunction):
    def __init__(self):
        self.checkpoint_completed = False
        self.failed = False

    def notify_checkpoint_complete(self, checkpoint_id):
        self.checkpoint_completed = True

    def map(self, value):
        if self.checkpoint_completed and not self.failed:
            self.failed = True
            raise RuntimeError("induced worker failure")
        return value


class GatedCollectionSource(FromCollectionSource):
    """Deterministic fault-tolerance source (the
    StreamFaultToleranceTestBase pattern, SURVEY.md §4.4): once most
    records are out, trickle the tail one record per step until the
    induced failure has happened, so the checkpoint trigger → barrier →
    ack → notify round trip always completes while records still flow
    through the failing mapper.  The gate rides on a CLASS attribute
    because the source factory deep-copies the function per subtask —
    instance references would be cloned away from the shared failer."""

    gate = None  # shared FailOnceAfterCheckpoint, set by the test
    HOLD = 600   # tail records reserved for the trickle phase

    def emit_step(self, ctx, max_records):
        gate = type(self).gate
        free_until = len(self.items) - self.HOLD
        if (gate is not None and not gate.failed
                and self.offset >= free_until):
            if self.offset >= len(self.items):
                return False  # runway exhausted — finish, let asserts fail
            import time as _t
            _t.sleep(0.001)
            return super().emit_step(ctx, 1)
        return super().emit_step(ctx, max_records)


def test_minicluster_exactly_once_recovery():
    """Worker fails mid-stream after a checkpoint; the master restarts
    the job from the latest snapshot (the multi-worker
    EventTimeWindowCheckpointingITCase shape)."""
    records = _records(n_keys=6, per_key=300)
    sink = CollectSink()
    failer = FailOnceAfterCheckpoint()
    GatedCollectionSource.gate = failer
    env = StreamExecutionEnvironment()
    env.use_mini_cluster(2)
    env.enable_checkpointing(10)
    env.set_restart_strategy("fixed_delay", restart_attempts=3, delay_ms=0)
    (env.add_source(GatedCollectionSource(records, timestamped=True),
                    name="gated_source")
        .map(failer, name="failer")
        .key_by(lambda v: v[0])
        .time_window(Time.milliseconds_of(1000))
        .aggregate(SumAgg())
        .add_sink(sink))
    result = env.execute("mini-exactly-once")
    assert failer.failed
    assert result.restarts == 1
    assert result.checkpoints_completed >= 1
    assert sum(sink.values) == 6 * 300


def test_minicluster_checkpoint_gauges_and_latency():
    """Metric surface parity with LocalExecutor: checkpoint gauges and
    latency histograms exist on the mini-cluster path too."""
    records = _records(n_keys=4, per_key=2000)
    env = StreamExecutionEnvironment()
    env.use_mini_cluster(2)
    env.enable_checkpointing(1)
    env.set_latency_tracking_interval(5)
    sink = CollectSink()
    (env.from_collection(records, timestamped=True)
        .key_by(lambda v: v[0])
        .time_window(Time.milliseconds_of(500))
        .aggregate(SumAgg())
        .add_sink(sink))
    result = env.execute("mini-metrics")
    assert result.checkpoints_completed >= 1
    dump = env.get_metric_registry().dump()
    assert dump["mini-metrics.checkpointing.numberOfCompletedCheckpoints"] >= 1
    assert dump["mini-metrics.checkpointing.lastCompletedCheckpointId"] >= 1
    assert any(".latency." in k for k in dump), "no latency histograms"
    # numRecordsIn reflects this attempt's records, once each
    ins = [v for k, v in dump.items() if k.endswith("numRecordsIn")]
    assert sum(ins) > 0


def test_minicluster_cancellation():
    import itertools

    from flink_tpu.streaming.sources import SourceFunction

    class Infinite(SourceFunction):
        def __init__(self):
            self._running = True

        def run(self, ctx):
            for i in itertools.count():
                if not self._running:
                    return
                ctx.collect(i)

        def cancel(self):
            self._running = False

    env = StreamExecutionEnvironment()
    env.use_mini_cluster(2)
    sink = CollectSink()
    env.add_source(Infinite()).map(lambda v: v).add_sink(sink)
    client = env.execute_async("mini-cancel")
    import time as _t
    _t.sleep(0.2)
    client.cancel()
    result = client.wait(timeout=10)
    assert result.cancelled


# ---------------------------------------------------------------------
# Mesh engine driven from the JobGraph (the full framework path)
# ---------------------------------------------------------------------

def _mesh_job(env, events, agg, size_ms=1000):
    sink = CollectSink()
    stream = env.from_collection(events)
    stream = stream.assign_timestamps_and_watermarks(
        BoundedOutOfOrdernessTimestampExtractor(0, lambda e: e[1]))
    (stream.key_by(lambda e: e[0])
        .window(TumblingEventTimeWindows.of(size_ms))
        .aggregate(agg, window_function=(
            lambda key, w, vals: [(key, w.start, vals[0])]))
        .add_sink(sink))
    return sink


def _sorted_events(n=400, n_keys=40, horizon=4000, seed=1):
    rng = np.random.default_rng(seed)
    return sorted(((int(k), int(t)) for k, t in
                   zip(rng.integers(0, n_keys, n),
                       rng.integers(0, horizon, n))), key=lambda e: e[1])


def test_mesh_window_job_on_minicluster(mesh):
    """keyBy().window().aggregate(device_agg) over the 8-device mesh,
    executed by the multi-worker MiniCluster from a JobGraph — the
    VERDICT r1 'connect the mesh path to the framework' milestone."""
    events = _sorted_events()
    env = StreamExecutionEnvironment()
    env.set_mesh(mesh).use_mini_cluster(2)
    env.set_parallelism(2)
    sink = _mesh_job(env, events, CountAggregate())
    env.execute("mesh-window-job")
    expect = collections.Counter()
    for k, t in events:
        expect[(k, t - t % 1000)] += 1
    got = {(k, s): int(v) for (k, s, v) in sink.values}
    assert got == dict(expect)


def test_mesh_window_job_differential_vs_scalar(mesh):
    """Mesh path vs scalar WindowOperator on identical input — the
    differential-testing spine applied to the sharded engine."""
    events = _sorted_events(n=600, n_keys=25, horizon=3000, seed=9)

    env1 = StreamExecutionEnvironment()
    env1.set_mesh(mesh)
    sink1 = _mesh_job(env1, events, CountAggregate())
    env1.execute("mesh")

    env2 = StreamExecutionEnvironment()
    sink2 = CollectSink()
    stream = env2.from_collection(events)
    stream = stream.assign_timestamps_and_watermarks(
        BoundedOutOfOrdernessTimestampExtractor(0, lambda e: e[1]))
    (stream.key_by(lambda e: e[0])
        .window(TumblingEventTimeWindows.of(1000))
        .disable_device_operator()
        .aggregate(CountAggregate(), window_function=(
            lambda key, w, vals: [(key, w.start, vals[0])]))
        .add_sink(sink2))
    env2.execute("scalar")

    got1 = {(k, s): int(v) for (k, s, v) in sink1.values}
    got2 = {(k, s): int(v) for (k, s, v) in sink2.values}
    assert got1 == got2


def test_mesh_window_job_checkpoint_recovery(mesh):
    """Failure + restart with the mesh engine state snapshot/restored
    through the barrier checkpoint path."""
    events = _sorted_events(n=900, n_keys=12, horizon=3000, seed=4)
    failer = FailOnceAfterCheckpoint()
    env = StreamExecutionEnvironment()
    env.set_mesh(mesh)
    env.enable_checkpointing(10)
    env.set_restart_strategy("fixed_delay", restart_attempts=3, delay_ms=0)
    sink = CollectSink()
    stream = env.from_collection(events)
    stream = stream.map(failer, name="failer")
    stream = stream.assign_timestamps_and_watermarks(
        BoundedOutOfOrdernessTimestampExtractor(0, lambda e: e[1]))
    (stream.key_by(lambda e: e[0])
        .window(TumblingEventTimeWindows.of(1000))
        .aggregate(CountAggregate(), window_function=(
            lambda key, w, vals: [(key, w.start, vals[0])]))
        .add_sink(sink))
    result = env.execute("mesh-recovery")
    assert failer.failed
    assert result.restarts == 1
    expect = collections.Counter()
    for k, t in events:
        expect[(k, t - t % 1000)] += 1
    got = {(k, s): int(v) for (k, s, v) in sink.values}
    assert got == dict(expect)


# ---------------------------------------------------------------------
# MeshSlidingWindows: pane-composed sliding on the mesh
# ---------------------------------------------------------------------

def test_mesh_sliding_counts_match_reference(mesh):
    from flink_tpu.parallel.mesh_windows import MeshSlidingWindows
    eng = MeshSlidingWindows(CountAggregate(), 3000, 1000, mesh,
                             capacity_per_window_shard=256, step_batch=64)
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 40, 600)
    ts = np.sort(rng.integers(0, 6000, 600))
    eng.process_batch(keys, ts)
    eng.advance_watermark(20_000)
    expect = collections.Counter()
    for k, t in zip(keys.tolist(), ts.tolist()):
        pane = t - t % 1000
        for w in range(pane - 2000, pane + 1000, 1000):
            expect[(k, w, w + 3000)] += 1
    got = {(k, s, e): v for (k, v, s, e) in eng.emitted}
    assert got == dict(expect)


def test_mesh_sliding_incremental_watermarks_match_vectorized(mesh):
    from flink_tpu.parallel.mesh_windows import MeshSlidingWindows
    from flink_tpu.streaming.vectorized import VectorizedSlidingWindows
    rng = np.random.default_rng(5)
    n = 800
    keys = rng.integers(0, 30, n).astype(np.uint64)
    ts = np.sort(rng.integers(0, 8000, n))
    vals = rng.random(n).astype(np.float32)

    ref = VectorizedSlidingWindows(SumAggregate(), 2000, 1000,
                                   initial_capacity=512)
    ref.process_batch(keys, ts, vals, key_hashes=None)
    ref.advance_watermark(30_000)

    eng = MeshSlidingWindows(SumAggregate(), 2000, 1000, mesh,
                             capacity_per_window_shard=128, step_batch=64)
    CH = 200
    for i in range(0, n, CH):
        sl = slice(i, i + CH)
        eng.process_batch(keys[sl], ts[sl], vals[sl])
        eng.advance_watermark(int(ts[sl][-1]) - 1)
    eng.advance_watermark(30_000)

    want = {(int(k), s, e): round(float(r), 3)
            for k, r, s, e in ref.emitted}
    got = {(int(k), s, e): round(float(r), 3)
           for k, r, s, e in eng.emitted}
    assert got == want


def test_mesh_sliding_snapshot_restore(mesh):
    from flink_tpu.parallel.mesh_windows import MeshSlidingWindows
    rng = np.random.default_rng(7)
    n = 400
    keys = rng.integers(0, 20, n)
    ts = np.sort(rng.integers(0, 5000, n))

    ref = MeshSlidingWindows(CountAggregate(), 2000, 1000, mesh,
                             capacity_per_window_shard=128, step_batch=64)
    ref.process_batch(keys, ts)
    ref.advance_watermark(20_000)

    a = MeshSlidingWindows(CountAggregate(), 2000, 1000, mesh,
                           capacity_per_window_shard=128, step_batch=64)
    a.process_batch(keys[:200], ts[:200])
    a.advance_watermark(int(ts[199]) - 1)
    snap = a.snapshot()
    b = MeshSlidingWindows(CountAggregate(), 2000, 1000, mesh,
                           capacity_per_window_shard=128, step_batch=64)
    b.restore(snap)
    b.process_batch(keys[200:], ts[200:])
    b.advance_watermark(20_000)
    combined = {(int(k), s, e): v for k, v, s, e in a.emitted}
    for k, v, s, e in b.emitted:
        combined[(int(k), s, e)] = v
    want = {(int(k), s, e): v for k, v, s, e in ref.emitted}
    assert combined == want


def test_mesh_sliding_parked_pane_not_lost(mesh):
    """Data spanning more panes than usable ring slots, then one big
    watermark: windows must not fire while one of their panes is
    parked (code-review regression — pane 6000's records were lost)."""
    from flink_tpu.parallel.mesh_windows import MeshSlidingWindows
    eng = MeshSlidingWindows(CountAggregate(), 2000, 1000, mesh,
                             capacity_per_window_shard=64, step_batch=32,
                             extra_ring=4)  # usable ring = 6 panes
    rng = np.random.default_rng(11)
    n = 300
    keys = rng.integers(0, 10, n)
    ts = rng.integers(0, 10_000, n)  # 10 panes > 6 usable slots
    eng.process_batch(keys, ts)
    eng.advance_watermark(50_000)
    expect = collections.Counter()
    for k, t in zip(keys.tolist(), ts.tolist()):
        pane = t - t % 1000
        for w in range(pane - 1000, pane + 1000, 1000):
            expect[(k, w, w + 2000)] += 1
    got = {(k, s, e): v for (k, v, s, e) in eng.emitted}
    assert got == dict(expect)


def test_mesh_sliding_blocked_window_fires_on_later_call(mesh):
    """A window due at watermark W but blocked on a parked pane must
    fire on a LATER advance_watermark call once the pane unparks —
    not vanish behind the fired horizon (round-2 advisor finding:
    _fired_horizon advanced past skipped windows)."""
    from flink_tpu.parallel.mesh_windows import MeshSlidingWindows

    def build():
        return MeshSlidingWindows(CountAggregate(), 2000, 1000, mesh,
                                  capacity_per_window_shard=64,
                                  step_batch=32, extra_ring=4)

    eng = build()
    # pane 6000 claims ring slot (6000//1000) % 6 == 0 first...
    eng.process_batch(np.array([1, 1, 1]), np.array([6500, 6600, 6700]))
    # ...then pane 0 (same slot 0) arrives out of order and parks
    eng.process_batch(np.array([2, 2]), np.array([500, 600]))
    # windows [-1000,1000) and [0,2000) are due but blocked on the
    # parked pane — nothing may fire yet
    assert eng.advance_watermark(1999) == 0
    assert eng.emitted == []
    # blocked windows survive a checkpoint cycle too
    restored = build()
    restored.restore(eng.snapshot())
    for e in (eng, restored):
        # pane 6000's windows fire and prune, slot 0 frees, pane 0
        # unparks, and the two previously-blocked windows fire
        e.advance_watermark(7999)
        got = {(k, s, e_): v for (k, v, s, e_) in e.emitted}
        assert got == {(2, -1000, 1000): 2, (2, 0, 2000): 2,
                       (1, 5000, 7000): 3, (1, 6000, 8000): 3}


def test_mesh_sliding_window_job_on_minicluster(mesh):
    """keyBy().window(Sliding...).aggregate(device_agg) over the mesh,
    executed from a JobGraph — the sliding twin of the tumbling mesh
    job (engine_for_assigner routes sliding+mesh to
    MeshSlidingWindows)."""
    from flink_tpu.streaming.windowing import SlidingEventTimeWindows
    events = _sorted_events(n=500, n_keys=30, horizon=5000, seed=13)
    env = StreamExecutionEnvironment()
    env.set_mesh(mesh).use_mini_cluster(2)
    env.set_parallelism(2)
    sink = CollectSink()
    stream = env.from_collection(events)
    stream = stream.assign_timestamps_and_watermarks(
        BoundedOutOfOrdernessTimestampExtractor(0, lambda e: e[1]))
    (stream.key_by(lambda e: e[0])
        .window(SlidingEventTimeWindows.of(2000, 1000))
        .aggregate(CountAggregate(), window_function=(
            lambda key, w, vals: [(key, w.start, w.end, vals[0])]))
        .add_sink(sink))
    env.execute("mesh-sliding-window-job")
    expect = collections.Counter()
    for k, t in events:
        pane = t - t % 1000
        for w in range(pane - 1000, pane + 1000, 1000):
            expect[(k, w, w + 2000)] += 1
    got = {(k, s, e): int(v) for (k, s, e, v) in sink.values}
    assert got == dict(expect)
