"""Metrics subsystem: metric types, groups/registry/reporters, and the
runtime wiring (numRecordsIn/Out, numLateRecordsDropped, latency
markers, checkpoint gauges).

Mirrors the reference's metric expectations: TaskIOMetricGroup counters
wired into the input processor (StreamInputProcessor.java:182),
WindowOperator.numLateRecordsDropped (WindowOperator.java:138),
CheckpointStatsTracker gauges, and LatencyMarker-fed histograms.
"""

import time

import pytest

from flink_tpu.core.functions import AggregateFunction
from flink_tpu.runtime.metrics import (
    Counter,
    Histogram,
    JsonLinesReporter,
    Meter,
    MetricRegistry,
    PrometheusTextReporter,
)
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.sources import CollectSink
from flink_tpu.streaming.windowing import Time


class SumAgg(AggregateFunction):
    def create_accumulator(self):
        return 0

    def add(self, value, acc):
        return acc + value[1]

    def get_result(self, acc):
        return acc

    def merge(self, a, b):
        return a + b


# ---------------------------------------------------------------------------
# metric types
# ---------------------------------------------------------------------------

def test_counter():
    c = Counter()
    c.inc()
    c.inc(5)
    c.dec(2)
    assert c.get_count() == 4


def test_histogram_statistics():
    h = Histogram(window=100)
    for v in range(1, 101):
        h.update(v)
    s = h.get_statistics()
    assert s.count == 100
    assert s.min == 1 and s.max == 100
    assert abs(s.mean - 50.5) < 1e-9
    assert s.quantile(0.5) == 51
    assert s.quantile(0.99) == 100


def test_histogram_sliding_window_evicts_oldest():
    h = Histogram(window=10)
    for v in range(100):
        h.update(v)
    s = h.get_statistics()
    assert h.get_count() == 100  # total updates
    assert s.count == 10         # reservoir
    assert s.min == 90


def test_meter_rate():
    t = [0.0]
    m = Meter(clock=lambda: t[0], window_s=60.0)
    for _ in range(10):
        t[0] += 1.0
        m.mark_event(6)
    assert m.get_count() == 60
    assert m.get_rate() == pytest.approx(6.0, rel=0.2)


# ---------------------------------------------------------------------------
# groups / registry / reporters
# ---------------------------------------------------------------------------

def test_group_scope_and_dump():
    reg = MetricRegistry()
    op = reg.job_group("jobA").add_group("map").add_group("0")
    op.counter("numRecordsIn").inc(7)
    op.gauge("queue", lambda: 3)
    dump = reg.dump()
    assert dump["jobA.map.0.numRecordsIn"] == 7
    assert dump["jobA.map.0.queue"] == 3


def test_group_reuse_same_child():
    reg = MetricRegistry()
    g1 = reg.job_group("j").add_group("x")
    g2 = reg.job_group("j").add_group("x")
    assert g1 is g2
    c = g1.counter("c")
    assert g2.counter("c") is c


def test_prometheus_render():
    reg = MetricRegistry()
    g = reg.job_group("job-1").add_group("op")
    g.counter("numRecordsIn").inc(3)
    h = g.histogram("lat")
    h.update(5.0)
    rep = PrometheusTextReporter()
    reg.add_reporter(rep)
    reg.report()
    text = rep.render()
    assert "flink_tpu_job_1_op_numRecordsIn 3" in text
    assert "flink_tpu_job_1_op_lat_p99 5.0" in text


def test_json_lines_reporter(tmp_path):
    import json
    path = str(tmp_path / "metrics.jsonl")
    reg = MetricRegistry()
    reg.job_group("j").counter("c").inc(2)
    reg.add_reporter(JsonLinesReporter(path=path))
    reg.report()
    reg.report()
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 2
    assert json.loads(lines[-1])["metrics"]["j.c"] == 2


# ---------------------------------------------------------------------------
# runtime wiring
# ---------------------------------------------------------------------------

def _records(n_keys=4, per_key=50):
    return [((f"k{k}", 1), i * 10)
            for i in range(per_key) for k in range(n_keys)]


def test_job_io_metrics_and_window_counters():
    env = StreamExecutionEnvironment()
    sink = CollectSink()
    (env.from_collection(_records(), timestamped=True)
        .key_by(lambda v: v[0])
        .time_window(Time.milliseconds_of(100))
        .aggregate(SumAgg())
        .add_sink(sink))
    env.execute("metrics-job")

    dump = env.get_metric_registry().dump()
    rec_in = {k: v for k, v in dump.items() if k.endswith("numRecordsIn")}
    rec_out = {k: v for k, v in dump.items() if k.endswith("numRecordsOut")}
    # the window vertex consumed every source record
    assert sum(rec_in.values()) == 200
    # source's records-out counted at its router
    assert sum(rec_out.values()) >= 200
    # the window operator registered its late-drop counter group
    late = [v for k, v in dump.items() if k.endswith("numLateRecordsDropped")]
    assert late and sum(late) == 0


def test_late_records_dropped_counter():
    from flink_tpu.streaming.sources import AscendingTimestampExtractor

    env = StreamExecutionEnvironment()
    sink = CollectSink()
    # strongly out-of-order: a record far in the past after the
    # watermark advanced beyond its window + no allowed lateness
    records = [(1, 0), (1, 5000), (1, 10)]
    (env.from_collection(records)
        .assign_timestamps_and_watermarks(
            AscendingTimestampExtractor(lambda t: t[1]))
        .key_by(lambda v: v[0])
        .time_window(Time.milliseconds_of(100))
        .aggregate(SumAgg())
        .add_sink(sink))
    env.execute("late-drop")
    dump = env.get_metric_registry().dump()
    late = sum(v for k, v in dump.items()
               if k.endswith("numLateRecordsDropped"))
    assert late == 1


def test_checkpoint_gauges():
    env = StreamExecutionEnvironment()
    env.enable_checkpointing(5)
    (env.from_collection(_records(per_key=500), timestamped=True)
        .key_by(lambda v: v[0])
        .time_window(Time.milliseconds_of(100))
        .aggregate(SumAgg())
        .add_sink(CollectSink()))
    result = env.execute("cp-metrics")
    assert result.checkpoints_completed >= 1
    dump = env.get_metric_registry().dump()
    assert dump["cp-metrics.checkpointing.numberOfCompletedCheckpoints"] \
        == result.checkpoints_completed
    assert dump["cp-metrics.checkpointing.lastCompletedCheckpointId"] >= 1
    assert dump["cp-metrics.checkpointing.lastCheckpointSize"] > 0


def test_latency_markers_flow_to_histograms():
    env = StreamExecutionEnvironment()
    env.set_latency_tracking_interval(0)  # every executor loop pass
    (env.from_collection(_records(n_keys=2, per_key=2000),
                         timestamped=True)
        .key_by(lambda v: v[0])  # breaks the chain: marker crosses an edge
        .time_window(Time.milliseconds_of(100))
        .aggregate(SumAgg())
        .add_sink(CollectSink()))
    env.execute("latency-job")
    dump = env.get_metric_registry().dump()
    lat = {k: v for k, v in dump.items() if ".latency." in k}
    assert lat, f"no latency histograms in {list(dump)[:10]}"
    h = next(iter(lat.values()))
    assert h["count"] >= 1
    assert h["p99"] >= 0


def test_meter_rate_zero_after_window_expires():
    """Regression: get_rate() must clamp to 0.0 once all retained
    events predate the window — not extrapolate over dead events or
    go negative."""
    t = [0.0]
    m = Meter(clock=lambda: t[0], window_s=60.0)
    m.mark_event(10)
    t[0] = 30.0
    assert m.get_rate() > 0.0
    t[0] = 120.0  # the single retained event is now outside the window
    assert m.get_rate() == 0.0
    t[0] = 10_000.0
    assert m.get_rate() == 0.0
    assert m.get_count() == 10  # count is lifetime, unaffected
    # rate is never negative at any probe point
    t[0] = 10_001.0
    m.mark_event(1)
    for probe in (10_001.0, 10_030.0, 10_061.0, 10_500.0):
        t[0] = probe
        assert m.get_rate() >= 0.0


def _parse_prometheus(text):
    """Tiny exposition-format parser for the round-trip test: returns
    ({name: value}, {name: type}, {name: help}, [flag comments])."""
    samples, types, helps, flags = {}, {}, {}, []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(None, 3)
            types[name] = mtype
        elif line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            helps[name] = help_text
        elif line.startswith("#"):
            flags.append(line)
        else:
            name, value = line.rsplit(None, 1)
            samples[name] = float(value)
    return samples, types, helps, flags


def test_prometheus_round_trip_types_and_nan():
    reg = MetricRegistry()
    g = reg.job_group("rt").add_group("op")
    g.counter("records").inc(42)
    g.gauge("lag", lambda: 7.5,
            description="milliseconds behind the newest watermark")
    g.gauge("bad", lambda: float("nan"))
    g.gauge("label", lambda: "a-string")  # non-numeric: excluded
    rep = reg.add_reporter(PrometheusTextReporter())
    reg.report()
    samples, types, helps, flags = _parse_prometheus(rep.render())
    assert samples["flink_tpu_rt_op_records"] == 42.0
    assert samples["flink_tpu_rt_op_lag"] == 7.5
    # every sample is preceded by # TYPE gauge and a # HELP line
    for name in samples:
        assert types[name] == "gauge"
        assert name in helps
    # a described gauge carries its description as the HELP text
    assert helps["flink_tpu_rt_op_lag"] == \
        "milliseconds behind the newest watermark"
    # undescribed families fall back to the raw dotted key
    assert helps["flink_tpu_rt_op_records"] == "rt.op.records"
    # NaN is skipped from samples but flagged as a comment
    assert "flink_tpu_rt_op_bad" not in samples
    assert "flink_tpu_rt_op_bad" not in helps
    assert any("skipped NaN sample flink_tpu_rt_op_bad" in f for f in flags)
    # strings never leak into the exposition
    assert "flink_tpu_rt_op_label" not in samples


def test_report_envelope_carries_both_clocks():
    import time as _t
    reg = MetricRegistry()
    reg.job_group("env-job").counter("c").inc(3)
    before_wall = _t.time() * 1000.0
    before_mono = _t.monotonic() * 1000.0
    envelope = reg.report()
    assert set(envelope) == {"t_mono_ms", "t_wall_ms", "metrics"}
    assert before_wall <= envelope["t_wall_ms"] <= _t.time() * 1000.0
    assert before_mono <= envelope["t_mono_ms"] <= _t.monotonic() * 1000.0
    assert envelope["metrics"]["env-job.c"] == 3
    # reporters can peel the envelope off; flat dumps pass through
    from flink_tpu.runtime.metrics import unwrap_snapshot
    assert unwrap_snapshot(envelope) == envelope["metrics"]
    assert unwrap_snapshot({"a.b": 1}) == {"a.b": 1}


def test_latency_stats_caches_histograms():
    from flink_tpu.runtime.metrics import LatencyStats

    class _Marker:
        operator_id = "src-1"
        subtask_index = 0

    reg = MetricRegistry()
    stats = LatencyStats(reg.job_group("lat-cache"))
    stats.record(_Marker(), "sink-1", 5.0)
    h1 = stats._histograms[("src-1", 0, "sink-1")]
    stats.record(_Marker(), "sink-1", 7.0)
    assert stats._histograms[("src-1", 0, "sink-1")] is h1
    assert len(stats._histograms) == 1
    assert h1.get_statistics().count == 2
    # a different (marker, operator) pair gets its own histogram
    stats.record(_Marker(), "sink-2", 1.0)
    assert len(stats._histograms) == 2
    dump = reg.dump()
    assert dump["lat-cache.latency.source_src-1_0.operator_sink-1"][
        "count"] == 2
