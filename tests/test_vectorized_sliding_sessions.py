"""Device-path sliding + session windows: differential tests against
the scalar WindowOperator (the semantics spec) on random streams."""

import numpy as np
import pytest

from flink_tpu.core.state import AggregatingStateDescriptor
from flink_tpu.ops.device_agg import CountAggregate, SumAggregate
from flink_tpu.ops.sketches import HyperLogLogAggregate
from flink_tpu.streaming.harness import OneInputStreamOperatorTestHarness
from flink_tpu.streaming.vectorized import VectorizedSlidingWindows
from flink_tpu.streaming.vectorized_sessions import VectorizedSessionWindows
from flink_tpu.streaming.window_operator import WindowOperator
from flink_tpu.streaming.windowing import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
    Time,
    TimeWindow,
)


class _KVSum(SumAggregate):
    def __init__(self):
        super().__init__(np.float32)

    def extract_value(self, value):
        return value[1] if isinstance(value, tuple) else value


class _KVCount(CountAggregate):
    pass


def scalar_window_results(assigner, agg, records, watermarks_at):
    """Run (key, value, ts) records through the real WindowOperator,
    interleaving watermarks, and collect (key, result, start, end)."""
    def fn(key, window, elements):
        for v in elements:
            yield (key, float(v), window.start, window.end)

    op = WindowOperator(assigner,
                        AggregatingStateDescriptor("diff", agg),
                        window_function=fn)
    h = OneInputStreamOperatorTestHarness(op, key_selector=lambda t: t[0],
                                          state_backend="heap")
    h.open()
    wm_iter = iter(watermarks_at)
    next_wm = next(wm_iter, None)
    for i, (k, v, ts) in enumerate(records):
        if next_wm is not None and i == next_wm[0]:
            h.process_watermark(next_wm[1])
            next_wm = next(wm_iter, None)
        h.process_element((k, v), ts)
    h.process_watermark(2**62)
    out = h.extract_output_values()
    h.close()
    return sorted((int(k), round(r, 2), s, e) for k, r, s, e in out)


# ---------------------------------------------------------------------
# sliding (pane-composed)
# ---------------------------------------------------------------------

def test_sliding_matches_window_operator_sum():
    rng = np.random.default_rng(11)
    n = 6000
    keys = rng.integers(0, 40, n)
    ts = rng.integers(0, 20_000, n)
    vals = rng.random(n).astype(np.float32)
    size, slide = 5000, 1000

    vec = VectorizedSlidingWindows(_KVSum(), size, slide,
                                   initial_capacity=64)
    half = n // 2
    vec.process_batch(keys[:half], ts[:half], vals[:half])
    vec.advance_watermark(9_999)
    # second half: drop records that are now late, same as the operator
    vec.process_batch(keys[half:], ts[half:], vals[half:])
    vec.advance_watermark(2**62)

    records = [(int(keys[i]), float(vals[i]), int(ts[i])) for i in range(n)]
    want = scalar_window_results(
        SlidingEventTimeWindows.of(Time.milliseconds_of(size),
                                   Time.milliseconds_of(slide)),
        _KVSum(), records, [(half, 9_999)])
    got = sorted((int(k), round(float(r), 2), s, e)
                 for k, r, s, e in vec.emitted)
    assert got == want


def test_sliding_pane_state_is_not_replicated():
    """The engine's whole point: per-record state writes are paid once
    per pane, not once per overlapping window."""
    size, slide = 10_000, 1000  # overlap factor 10
    vec = VectorizedSlidingWindows(CountAggregate(), size, slide,
                                   initial_capacity=64)
    keys = np.zeros(1000, np.int64)
    ts = np.arange(1000)  # all within pane [0, 1000)
    vec.process_batch(keys, ts)
    # exactly ONE live pane shard, one slot — not 10 replicated states
    assert len(vec.windows) == 1
    assert vec.arena.high_water <= 2  # key slot (+ scratch)
    vec.advance_watermark(2**62)
    # the single pane feeds all 10 windows that contain it
    assert len(vec.emitted) == 10
    assert all(int(r) == 1000 for _, r, _, _ in vec.emitted)


def test_sliding_hll_merges_across_panes():
    """Distinct-count across panes must merge sketches, not add them."""
    agg = HyperLogLogAggregate(11)
    size, slide = 4000, 1000
    vec = VectorizedSlidingWindows(agg, size, slide, initial_capacity=32)
    # same 1000 users appear in FOUR consecutive panes for one key
    users = np.arange(1000, dtype=np.uint64)
    for pane in range(4):
        ts = np.full(1000, pane * slide + 5)
        vec.process_batch(np.zeros(1000, np.int64), ts, users)
    vec.advance_watermark(2**62)
    # window [0,4000) contains all four panes; duplicates across panes
    # must not inflate the estimate
    full = [r for _, r, s, e in vec.emitted if s == 0 and e == 4000]
    assert len(full) == 1
    assert abs(full[0] - 1000) / 1000 < 0.05


def test_sliding_rejects_unaligned():
    with pytest.raises(ValueError):
        VectorizedSlidingWindows(CountAggregate(), 5000, 1500)


def test_sliding_late_records_counted():
    vec = VectorizedSlidingWindows(CountAggregate(), 2000, 1000)
    vec.process_batch(np.array([1]), np.array([500]))
    vec.advance_watermark(2999)  # all windows containing ts=500 fired
    vec.process_batch(np.array([1, 1]), np.array([600, 3500]))
    assert vec.num_late_dropped == 1  # ts=600 fully late; 3500 live
    vec.advance_watermark(2**62)
    # ts=500 appears in windows [-1000,1000) and [0,2000): 2 fires
    # ts=3500 appears in [2000,4000) and [3000,5000): 2 fires
    assert len(vec.emitted) == 4


# ---------------------------------------------------------------------
# sessions (batched merge)
# ---------------------------------------------------------------------

def test_sessions_match_window_operator_sum():
    rng = np.random.default_rng(23)
    n = 4000
    keys = rng.integers(0, 25, n)
    # clustered timestamps → real session structure
    ts = (rng.integers(0, 40, n) * 1000
          + rng.integers(0, 300, n)).astype(np.int64)
    vals = rng.random(n).astype(np.float32)
    gap = 700

    vec = VectorizedSessionWindows(_KVSum(), gap, initial_capacity=64)
    third = n // 3
    vec.process_batch(keys[:third], ts[:third], vals[:third])
    vec.advance_watermark(12_000)
    vec.process_batch(keys[third:2 * third], ts[third:2 * third],
                      vals[third:2 * third])
    vec.advance_watermark(25_000)
    vec.process_batch(keys[2 * third:], ts[2 * third:], vals[2 * third:])
    vec.advance_watermark(2**62)

    records = [(int(keys[i]), float(vals[i]), int(ts[i])) for i in range(n)]
    want = scalar_window_results(
        EventTimeSessionWindows.with_gap(Time.milliseconds_of(gap)),
        _KVSum(), records, [(third, 12_000), (2 * third, 25_000)])
    got = sorted((int(k), round(float(r), 2), s, e)
                 for k, r, s, e in vec.emitted)
    assert got == want


def test_sessions_merge_within_and_across_batches():
    vec = VectorizedSessionWindows(_KVCount(), 100, initial_capacity=16)
    # batch 1: two separate sessions for key 7
    vec.process_batch(np.array([7, 7]), np.array([0, 500]))
    assert sum(len(s) for s in vec.table.values()) == 2
    # batch 2: a bridging record merges them into one
    vec.process_batch(np.array([7]), np.array([250]))
    # intervals [0,100) [250,350) [500,600) don't chain... still 3?
    # gap=100: 0..100, 250..350, 500..600 → no overlap → 3 sessions
    assert sum(len(s) for s in vec.table.values()) == 3
    # true bridges
    vec.process_batch(np.array([7, 7]), np.array([80, 170]))
    # 0..100 + 80..180 + 170..270 + 250..350 all chain → one [0,350)
    sessions = [s for lst in vec.table.values() for s in lst]
    assert len(sessions) == 2  # merged chain + [500,600)
    merged = min(sessions, key=lambda s: s.start)
    assert (merged.start, merged.end) == (0, 350)
    vec.advance_watermark(2**62)
    got = sorted((int(r), s, e) for _, r, s, e in vec.emitted)
    assert got == [(1, 500, 600), (4, 0, 350)]


def test_sessions_hll_distinct_across_merge():
    agg = HyperLogLogAggregate(11)
    vec = VectorizedSessionWindows(agg, 1000, initial_capacity=16)
    users = np.arange(2000, dtype=np.uint64)
    # two halves of the same session arrive in separate batches with
    # overlapping user populations
    vec.process_batch(np.zeros(1000, np.int64), np.full(1000, 0),
                      users[:1000])
    vec.process_batch(np.zeros(1500, np.int64), np.full(1500, 500),
                      users[500:2000])
    vec.advance_watermark(2**62)
    assert len(vec.emitted) == 1
    _, est, s, e = vec.emitted[0]
    assert (s, e) == (0, 1500)
    assert abs(est - 2000) / 2000 < 0.05  # merged, not double-counted


def test_sessions_late_drop_and_post_merge_leniency():
    vec = VectorizedSessionWindows(_KVCount(), 100)
    vec.process_batch(np.array([1]), np.array([1000]))
    vec.advance_watermark(500)
    # ts=100: solo window [100,200) ends before wm=500 and overlaps
    # nothing live → late
    vec.process_batch(np.array([1]), np.array([100]))
    assert vec.num_late_dropped == 1
    # ts=950: solo window [950,1050) would be late... but 1050 > 500,
    # and it overlaps the live [1000,1100) session → merges
    vec.process_batch(np.array([1]), np.array([950]))
    assert vec.num_late_dropped == 1
    vec.advance_watermark(2**62)
    assert [(int(r), s, e) for _, r, s, e in vec.emitted] == [(2, 950, 1100)]


def test_sessions_slot_reuse():
    vec = VectorizedSessionWindows(_KVCount(), 100, initial_capacity=8)
    for round_i in range(20):
        base = round_i * 10_000
        vec.process_batch(np.arange(4), np.full(4, base))
        vec.advance_watermark(base + 5000)
    assert len(vec.emitted) == 80
    # slots recycled: capacity stayed small
    assert vec.capacity <= 16
