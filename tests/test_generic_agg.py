"""Generic vectorized AggregateFunction tier (streaming/generic_agg.py).

Differential tests: every result must equal the scalar per-record
WindowOperator path (the reference semantics twin,
WindowOperator.java:291-421) on the same stream.
"""

import numpy as np
import pytest

from flink_tpu.core.functions import AggregateFunction
from flink_tpu.streaming.datastream import StreamExecutionEnvironment
from flink_tpu.streaming.generic_agg import (
    GenericLogSessionWindows,
    GenericLogSlidingWindows,
    GenericLogTumblingWindows,
    LiftedAggregate,
    columnify,
)
from flink_tpu.streaming.sources import CollectSink
from flink_tpu.streaming.windowing import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


class MeanMax(AggregateFunction):
    """Liftable: tuple accumulator, pure arithmetic add."""

    def create_accumulator(self):
        return (0.0, 0.0, -np.inf)

    def add(self, v, acc):
        s, c, m = acc
        return (s + v, c + 1.0, np.maximum(m, v))

    def get_result(self, acc):
        s, c, m = acc
        return (s / c, float(m))

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1], np.maximum(a[2], b[2]))


class Branchy(AggregateFunction):
    """Data-dependent control flow: must fail the lift probe and run
    the sorted-segment scalar fold."""

    def create_accumulator(self):
        return (0.0, 0)

    def add(self, v, acc):
        s, c = acc
        if v > 0.5:
            return (s + v * 2, c + 1)
        return (s + v, c + 1)

    def get_result(self, acc):
        return acc[0] / max(acc[1], 1)

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1])


class TupleValueAgg(AggregateFunction):
    """Consumes the full (key, x) element — the DataStream shape."""

    def create_accumulator(self):
        return 0.0

    def add(self, v, acc):
        return acc + v[1]

    def get_result(self, acc):
        return acc

    def merge(self, a, b):
        return a + b


def _stream(n=6000, keys=97, span=5000, seed=3):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, keys, n).astype(np.int64)
    t = np.sort(rng.integers(0, span, n).astype(np.int64))
    v = rng.random(n)
    return k, t, v


def _scalar_reference(keys, ts, vals, agg, size):
    st = {}
    for k, t, v in zip(keys.tolist(), ts.tolist(), vals.tolist()):
        w = t - t % size
        acc = st.get((w, k))
        if acc is None:
            acc = agg.create_accumulator()
        st[(w, k)] = agg.add(v, acc)
    return {(w, k): agg.get_result(a) for (w, k), a in st.items()}


@pytest.mark.parametrize("agg_cls,mode", [(MeanMax, "lifted"),
                                          (Branchy, "scalar")])
def test_tumbling_differential(agg_cls, mode):
    keys, ts, vals = _stream()
    agg = agg_cls()
    eng = GenericLogTumblingWindows(agg, 1000, compact_threshold=2048)
    for i in range(0, len(keys), 1500):
        eng.process_batch(keys[i:i+1500], ts[i:i+1500], vals[i:i+1500])
    eng.advance_watermark(10_000)
    assert eng.mode == mode
    got = {(s, k): r for k, r, s, e in eng.emitted}
    want = _scalar_reference(keys, ts, vals, agg, 1000)
    assert set(got) == set(want)
    for key in want:
        np.testing.assert_allclose(np.asarray(got[key], float),
                                   np.asarray(want[key], float),
                                   rtol=1e-9)


def test_force_scalar_opt_out():
    """force_scalar pins the scalar fold on an aggregate the probe
    would lift — results stay identical."""
    keys, ts, vals = _stream()

    class PinnedMeanMax(MeanMax):
        force_scalar = True

    agg = PinnedMeanMax()
    eng = GenericLogTumblingWindows(agg, 1000, compact_threshold=2048)
    for i in range(0, len(keys), 1500):
        eng.process_batch(keys[i:i+1500], ts[i:i+1500], vals[i:i+1500])
    eng.advance_watermark(10_000)
    assert eng.mode == "scalar"
    got = {(s, k): r for k, r, s, e in eng.emitted}
    want = _scalar_reference(keys, ts, vals, agg, 1000)
    assert set(got) == set(want)
    for key in want:
        np.testing.assert_allclose(np.asarray(got[key], float),
                                   np.asarray(want[key], float),
                                   rtol=1e-9)

    # the per-operator knob pins it without touching the aggregate
    from flink_tpu.streaming.generic_agg import GenericWindowOperator
    op = GenericWindowOperator(TumblingEventTimeWindows.of(1000),
                               MeanMax(), force_scalar=True)
    op._ensure_engine()
    assert op.engine.lift.mode == "scalar"


def test_sliding_differential():
    keys, ts, vals = _stream()
    agg = MeanMax()
    eng = GenericLogSlidingWindows(agg, 2000, 1000)
    for i in range(0, len(keys), 1500):
        eng.process_batch(keys[i:i+1500], ts[i:i+1500], vals[i:i+1500])
        eng.advance_watermark(int(ts[min(i + 1499, len(ts) - 1)]) - 1)
    eng.advance_watermark(20_000)
    st = {}
    for k, t, v in zip(keys.tolist(), ts.tolist(), vals.tolist()):
        pane = t - t % 1000
        for w in (pane - 1000, pane):
            acc = st.get((w, k)) or agg.create_accumulator()
            st[(w, k)] = agg.add(v, acc)
    want = {(w, k): agg.get_result(a) for (w, k), a in st.items()}
    got = {(s, k): r for k, r, s, e in eng.emitted}
    assert set(got) == set(want)
    for key in want:
        np.testing.assert_allclose(np.asarray(got[key], float),
                                   np.asarray(want[key], float),
                                   rtol=1e-9)


def test_session_differential():
    rng = np.random.default_rng(5)
    n, gap = 4000, 300
    keys = rng.integers(0, 37, n).astype(np.int64)
    ts = np.sort(rng.integers(0, 50_000, n).astype(np.int64))
    vals = rng.random(n)
    agg = MeanMax()
    eng = GenericLogSessionWindows(agg, gap)
    for i in range(0, n, 900):
        eng.process_batch(keys[i:i+900], ts[i:i+900], vals[i:i+900])
        eng.advance_watermark(int(ts[min(i + 899, n - 1)]) - 1)
    eng.advance_watermark(100_000)
    got = {(k, s, e): r for k, r, s, e in eng.emitted}
    rows = sorted(zip(keys.tolist(), ts.tolist(), vals.tolist()),
                  key=lambda r: (r[0], r[1]))
    want, cur = {}, None
    for k, t, v in rows:
        if cur is None or cur[0] != k or t - cur[2] > gap:
            if cur is not None:
                want[(cur[0], cur[1], cur[2] + gap)] = \
                    agg.get_result(cur[3])
            cur = [k, t, t, agg.create_accumulator()]
        cur[2] = t
        cur[3] = agg.add(v, cur[3])
    want[(cur[0], cur[1], cur[2] + gap)] = agg.get_result(cur[3])
    assert set(got) == set(want)
    for key in want:
        np.testing.assert_allclose(np.asarray(got[key], float),
                                   np.asarray(want[key], float),
                                   rtol=1e-9)


def test_string_keys_fall_back_to_numpy_sort():
    words = np.array(["ant", "bee", "cat", "ant", "bee", "ant"])
    ts = np.array([10, 20, 30, 40, 50, 60], np.int64)
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
    eng = GenericLogTumblingWindows(MeanMax(), 1000)
    eng.process_batch(words, ts, vals)
    eng.advance_watermark(2000)
    got = {k: r for k, r, s, e in eng.emitted}
    assert set(got) == {"ant", "bee", "cat"}
    np.testing.assert_allclose(got["ant"][0], (1 + 4 + 6) / 3)
    np.testing.assert_allclose(got["bee"][1], 5.0)


def test_late_records_dropped():
    eng = GenericLogTumblingWindows(MeanMax(), 1000)
    eng.process_batch(np.array([1, 2]), np.array([100, 200], np.int64),
                      np.array([1.0, 2.0]))
    eng.advance_watermark(999)
    assert len(eng.emitted) == 2
    eng.process_batch(np.array([1]), np.array([500], np.int64),
                      np.array([9.0]))
    assert eng.num_late_dropped == 1
    eng.advance_watermark(1999)
    assert len(eng.emitted) == 2  # nothing new fired


def test_session_late_record_merging_into_open_session_survives():
    """Merge-before-drop (ref: WindowOperator.java:308-343 — a late
    record merges with existing sessions FIRST; only a merged window
    behind the watermark is dropped): a straggler within the gap of an
    open session is accepted and extends it backwards."""
    eng = GenericLogSessionWindows(MeanMax(), 10)
    eng.process_batch(np.array([1, 1]), np.array([100, 108], np.int64),
                      np.array([1.0, 2.0]))
    eng.advance_watermark(105)  # session open: last ts 108
    # ts=95: own window [95,105) is late, but |100-95| <= gap
    eng.process_batch(np.array([1]), np.array([95], np.int64),
                      np.array([9.0]))
    assert eng.num_late_dropped == 0
    eng.advance_watermark(200)
    assert [(k, s, e) for k, _, s, e in eng.emitted] == [(1, 95, 118)]
    np.testing.assert_allclose(eng.emitted[0][1][1], 9.0)  # max


def test_session_late_record_chains_transitively():
    """A late row that only reaches an open session through ANOTHER
    late row in the same batch is revived too (the reference merges
    session by session until a fixpoint)."""
    eng = GenericLogSessionWindows(MeanMax(), 10)
    eng.process_batch(np.array([1]), np.array([110], np.int64),
                      np.array([1.0]))
    eng.advance_watermark(112)
    # 92 -> 101 (gap 9) -> 110 (gap 9): both late on their own horizon
    eng.process_batch(np.array([1, 1]), np.array([92, 101], np.int64),
                      np.array([2.0, 3.0]))
    assert eng.num_late_dropped == 0
    eng.advance_watermark(300)
    assert [(k, s, e) for k, _, s, e in eng.emitted] == [(1, 92, 120)]


def test_session_late_record_without_open_session_still_drops():
    eng = GenericLogSessionWindows(MeanMax(), 10)
    eng.process_batch(np.array([1]), np.array([100], np.int64),
                      np.array([1.0]))
    eng.advance_watermark(105)
    # too far behind the open session (gap 20 > 10)
    eng.process_batch(np.array([1]), np.array([80], np.int64),
                      np.array([5.0]))
    # an open session for ANOTHER key never revives
    eng.process_batch(np.array([2]), np.array([95], np.int64),
                      np.array([5.0]))
    assert eng.num_late_dropped == 2
    eng.advance_watermark(300)
    assert [(k, s, e) for k, _, s, e in eng.emitted] == [(1, 100, 110)]


def test_snapshot_restore_mid_window():
    keys, ts, vals = _stream(n=3000)
    agg = MeanMax()
    eng = GenericLogTumblingWindows(agg, 1000, compact_threshold=512)
    eng.process_batch(keys[:1500], ts[:1500], vals[:1500])
    eng.advance_watermark(int(ts[1499]) - 1)
    fired_before = list(eng.emitted)
    snap = eng.snapshot()

    eng2 = GenericLogTumblingWindows(agg, 1000, compact_threshold=512)
    eng2.restore(snap)
    for e in (eng, eng2):
        e.process_batch(keys[1500:], ts[1500:], vals[1500:])
        e.advance_watermark(10_000)
    tail1 = eng.emitted[len(fired_before):]
    tail2 = eng2.emitted
    got1 = {(s, k): r for k, r, s, e in tail1}
    got2 = {(s, k): r for k, r, s, e in tail2}
    assert set(got1) == set(got2)
    for key in got1:
        np.testing.assert_allclose(np.asarray(got1[key], float),
                                   np.asarray(got2[key], float),
                                   rtol=1e-9)


def test_restore_many_rescale_filters_keys():
    from flink_tpu.core.keygroups import make_key_group_keep_fn
    keys, ts, vals = _stream(n=2000)
    agg = MeanMax()
    eng = GenericLogTumblingWindows(agg, 1000)
    eng.process_batch(keys, ts, vals)
    snap = eng.snapshot()
    # split across 2 subtasks; union of both halves == unfiltered
    fired = {}
    for idx in (0, 1):
        part = GenericLogTumblingWindows(agg, 1000)
        keep = make_key_group_keep_fn(128, 2, idx)
        part.restore_many([snap], keep)
        part.advance_watermark(10_000)
        for k, r, s, e in part.emitted:
            assert (s, k) not in fired, "key emitted by both subtasks"
            fired[(s, k)] = r
    whole = GenericLogTumblingWindows(agg, 1000)
    whole.restore(snap)
    whole.advance_watermark(10_000)
    want = {(s, k): r for k, r, s, e in whole.emitted}
    assert set(fired) == set(want)


def _run_job(generic: bool, agg, records, assigner):
    env = StreamExecutionEnvironment()
    sink = CollectSink()
    ws = (env.from_collection(records, timestamped=True)
          .key_by(lambda v: v[0])
          .window(assigner))
    if not generic:
        ws.disable_device_operator()
    (ws.aggregate(agg,
                  window_function=lambda key, w, vals:
                  [(key, w.start, vals[0])])
     .add_sink(sink))
    env.execute("generic-agg-job")
    return sorted((k, s, tuple(np.atleast_1d(np.asarray(v, float))))
                  for k, s, v in sink.values)


def test_datastream_equals_scalar_window_operator():
    rng = np.random.default_rng(11)
    n = 4000
    ts = np.sort(rng.integers(0, 4000, n))
    records = [((int(k), float(x)), int(t)) for k, x, t in zip(
        rng.integers(0, 53, n), rng.random(n), ts)]
    assigner = TumblingEventTimeWindows.of(500)
    got = _run_job(True, TupleValueAgg(), records, assigner)
    want = _run_job(False, TupleValueAgg(), records, assigner)
    assert got == want and len(got) > 0


def test_datastream_sessions_generic():
    rng = np.random.default_rng(13)
    n = 2000
    ts = np.sort(rng.integers(0, 30_000, n))
    records = [((int(k), float(x)), int(t)) for k, x, t in zip(
        rng.integers(0, 23, n), rng.random(n), ts)]
    assigner = EventTimeSessionWindows.with_gap(37)
    got = _run_job(True, TupleValueAgg(), records, assigner)
    want = _run_job(False, TupleValueAgg(), records, assigner)
    assert got == want and len(got) > 0


def test_columnify_shapes():
    cols, spec = columnify([1.0, 2.0, 3.0])
    assert spec == "scalar" and len(cols) == 1
    cols, spec = columnify([(1, "a"), (2, "b")])
    assert spec == ("tuple", 2)
    cols, spec = columnify([{"a": 1}, {"b": 2}])
    assert cols is None
    cols, spec = columnify([(1, [2]), (3, [4])])
    assert cols is None


def test_lift_probe_result_demotion():
    class WeirdResult(AggregateFunction):
        def create_accumulator(self):
            return 0.0

        def add(self, v, acc):
            return acc + v

        def get_result(self, acc):
            # data-dependent branch in get_result only
            return float(acc) if acc > 1 else -1.0

        def merge(self, a, b):
            return a + b

    keys, ts, vals = _stream(n=800, keys=11)
    eng = GenericLogTumblingWindows(WeirdResult(), 1000)
    eng.process_batch(keys, ts, vals)
    eng.advance_watermark(10_000)
    assert eng.mode == "lifted"          # the fold lifts
    assert not eng.lift.result_lifted    # the result does not
    want = _scalar_reference(keys, ts, vals, WeirdResult(), 1000)
    got = {(s, k): r for k, r, s, e in eng.emitted}
    assert set(got) == set(want)
    for key in want:
        np.testing.assert_allclose(got[key], want[key], rtol=1e-9)


def test_sliding_idle_gap_fires_fast():
    """A week-long event-time gap at a small slide must not walk the
    gap one slide at a time (candidate ends come from live panes)."""
    import time as _time
    agg = MeanMax()
    eng = GenericLogSlidingWindows(agg, 30, 10)
    eng.process_batch(np.array([1, 2]), np.array([5, 15], np.int64),
                      np.array([1.0, 2.0]))
    t0 = _time.perf_counter()
    eng.advance_watermark(7 * 24 * 3600 * 1000)  # one week
    assert _time.perf_counter() - t0 < 1.0
    # all windows containing the two panes fired exactly once
    fired = {(s, k) for k, r, s, e in eng.emitted}
    # ts=5 lives in windows starting -20/-10/0; ts=15 in -10/0/10
    assert fired == {(-20, 1), (-10, 1), (0, 1),
                     (-10, 2), (0, 2), (10, 2)}
    # late data after the gap starts fresh windows without refiring
    eng.process_batch(np.array([3]),
                      np.array([7 * 24 * 3600 * 1000 + 25], np.int64),
                      np.array([9.0]))
    n_before = len(eng.emitted)
    eng.advance_watermark(7 * 24 * 3600 * 1000 + 100)
    assert len(eng.emitted) == n_before + 3  # 3 windows contain it


# ---------------------------------------------------------------------
# ahead-of-time liftability analysis vs the runtime probe
# ---------------------------------------------------------------------

def _probe_mode(agg_cls):
    """What the runtime probe decides for this aggregate (fresh
    engine, no static verdict applied)."""
    keys, ts, vals = _stream(n=400, keys=7)
    eng = GenericLogTumblingWindows(agg_cls(), 1000)
    eng.process_batch(keys, ts, vals)
    eng.advance_watermark(10_000)
    return eng.mode, eng.lift.result_lifted


@pytest.mark.parametrize("agg_cls", [MeanMax, Branchy, TupleValueAgg])
def test_static_verdict_consistent_with_probe(agg_cls):
    """The differential contract: anything the probe lifts must
    analyze LIFTABLE or INCONCLUSIVE (never falsely IMPURE or
    SCALAR_ONLY), and a conclusive scalar verdict must match a probe
    demotion."""
    from flink_tpu.analysis.liftability import analyze_aggregate
    report = analyze_aggregate(agg_cls())
    if agg_cls is TupleValueAgg:
        # probing TupleValueAgg with plain floats raises inside add
        # (v[1]); the DataStream tests cover its lifted path. Only
        # check the verdict here.
        assert report.verdict in ("LIFTABLE", "INCONCLUSIVE")
        return
    mode, result_lifted = _probe_mode(agg_cls)
    if mode == "lifted":
        assert report.verdict in ("LIFTABLE", "INCONCLUSIVE")
        if report.verdict == "LIFTABLE":
            # a conclusive result_liftable may not overclaim either
            assert not (report.result_liftable and not result_lifted)
    else:
        assert report.verdict != "LIFTABLE"


def test_static_verdict_zoo():
    """Pin the exact verdicts for the aggregate zoo."""
    from flink_tpu.analysis.liftability import analyze_aggregate
    r = analyze_aggregate(MeanMax())
    assert r.verdict == "LIFTABLE"
    assert not r.result_liftable      # float(m) in get_result
    r = analyze_aggregate(Branchy())
    assert r.verdict == "SCALAR_ONLY"
    assert any("branch" in s for s in r.reasons)
    r = analyze_aggregate(TupleValueAgg())
    assert r.verdict == "LIFTABLE" and r.result_liftable


def test_static_liftable_skips_probe():
    """A conclusive LIFTABLE verdict arms the probe-skip fast path:
    no scalar-reference replay (create_accumulator is called once for
    the structure and never per probe group), same results."""
    from flink_tpu.analysis.liftability import analyze_aggregate

    keys, ts, vals = _stream()
    agg = MeanMax()
    report = analyze_aggregate(agg)
    assert report.verdict == "LIFTABLE"
    # instrument AFTER analysis (a counting override in the class body
    # would itself be impure bytecode and flip the verdict)
    calls = []
    orig_create = agg.create_accumulator
    agg.create_accumulator = lambda: (calls.append(1), orig_create())[1]
    eng = GenericLogTumblingWindows(agg, 1000, compact_threshold=2048)
    eng.lift.apply_static(report)
    calls_before = len(calls)
    eng.process_batch(keys[:1500], ts[:1500], vals[:1500])
    assert eng.mode == "lifted"
    assert eng.lift.decided_by == "static"
    assert not eng.lift.result_lifted   # static verdict carried over
    # the probe's scalar reference would have called
    # create_accumulator once per group; the static path never does
    assert len(calls) == calls_before
    for i in range(1500, len(keys), 1500):
        eng.process_batch(keys[i:i+1500], ts[i:i+1500], vals[i:i+1500])
    eng.advance_watermark(10_000)
    got = {(s, k): r for k, r, s, e in eng.emitted}
    want = _scalar_reference(keys, ts, vals, MeanMax(), 1000)
    assert set(got) == set(want)
    for key in want:
        np.testing.assert_allclose(np.asarray(got[key], float),
                                   np.asarray(want[key], float),
                                   rtol=1e-9)


def test_static_scalar_verdict_locks_without_probe():
    from flink_tpu.analysis.liftability import analyze_aggregate
    eng = GenericLogTumblingWindows(Branchy(), 1000)
    eng.lift.apply_static(analyze_aggregate(Branchy()))
    assert eng.mode == "scalar"
    assert eng.lift.decided_by == "static"
    assert "branch" in eng.lift.fallback_reason
    keys, ts, vals = _stream(n=500, keys=7)
    eng.process_batch(keys, ts, vals)
    eng.advance_watermark(10_000)
    got = {(s, k): r for k, r, s, e in eng.emitted}
    want = _scalar_reference(keys, ts, vals, Branchy(), 1000)
    assert set(got) == set(want)
    for key in want:
        np.testing.assert_allclose(got[key], want[key], rtol=1e-9)


def test_operator_applies_static_verdict():
    """GenericWindowOperator wires the AOT verdict into its engine;
    force_probe opts back into the runtime probe."""
    from flink_tpu.streaming.generic_agg import GenericWindowOperator
    op = GenericWindowOperator(TumblingEventTimeWindows.of(1000),
                               MeanMax())
    op._ensure_engine()
    assert op.engine.lift._static_lift       # armed, probe will skip

    class ProbeMeanMax(MeanMax):
        force_probe = True

    op2 = GenericWindowOperator(TumblingEventTimeWindows.of(1000),
                                ProbeMeanMax())
    op2._ensure_engine()
    assert not op2.engine.lift._static_lift  # opted out
    assert op2.engine.lift.mode is None      # probe still in charge


def test_decided_by_survives_snapshot_restore():
    keys, ts, vals = _stream(n=800, keys=11)
    eng = GenericLogTumblingWindows(MeanMax(), 1000)
    eng.process_batch(keys, ts, vals)
    assert eng.lift.decided_by == "probe"
    snap = eng.snapshot()
    eng2 = GenericLogTumblingWindows(MeanMax(), 1000)
    eng2.restore(snap)
    assert eng2.mode == "lifted"
    assert eng2.lift.decided_by == "probe"
    # an old snapshot without the key degrades to "restore"
    snap.pop("decided_by", None)
    eng3 = GenericLogTumblingWindows(MeanMax(), 1000)
    eng3.restore(snap)
    assert eng3.lift.decided_by == "restore"


def test_scalar_fallback_warns_once(caplog):
    """Satellite: the silent scalar fallback now logs one structured
    warning naming the aggregate and the reason — once per (class,
    reason) pair."""
    import logging

    from flink_tpu.streaming import generic_agg as ga

    class Disagreeing(AggregateFunction):
        """Passes structurally, but the lifted fold diverges: max()
        collapses a column to one Python scalar."""

        def create_accumulator(self):
            return 0.0

        def add(self, v, acc):
            return max(acc, v)

        def get_result(self, acc):
            return acc

        def merge(self, a, b):
            return max(a, b)

        force_probe = True   # keep the runtime probe in charge

    ga._FALLBACK_WARNED.clear()
    keys, ts, vals = _stream(n=300, keys=5)
    with caplog.at_level(logging.WARNING, logger="flink_tpu.generic_agg"):
        eng = GenericLogTumblingWindows(Disagreeing(), 1000)
        eng.process_batch(keys, ts, vals)
        assert eng.mode == "scalar"
        # second engine, same aggregate class: no duplicate warning
        eng2 = GenericLogTumblingWindows(Disagreeing(), 1000)
        eng2.process_batch(keys, ts, vals)
    msgs = [r.message for r in caplog.records
            if "falls back" in r.message]
    assert len(msgs) == 1
    assert "Disagreeing" in msgs[0]
    assert eng.lift.fallback_reason is not None


def test_value_shape_change_demotes_to_object_rows():
    """A stream whose value shape changes mid-window demotes the
    engine to object-row mode with unchanged results (the per-record
    WindowOperator contract)."""
    agg = TupleValueAgg()
    eng = GenericLogTumblingWindows(agg, 1000)
    eng.process_batch(np.array([1, 2]), np.array([10, 20], np.int64),
                      [(1, 2.0), (2, 3.0)])
    assert eng.mode == "lifted"
    # same logical payload, now with a trailing tag field the
    # aggregate ignores — the spec k changes from 2 to 3
    eng.process_batch(np.array([1, 2]), np.array([30, 40], np.int64),
                      [(1, 5.0, "x"), (2, 7.0, "y")])
    assert eng.vspec is None and eng.mode == "scalar"
    eng.advance_watermark(2000)
    got = {k: r for k, r, s, e in eng.emitted}
    assert got == {1: 7.0, 2: 10.0}
