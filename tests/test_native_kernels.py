"""Property tests for the native log-engine kernels against pure-python
references: radix sort grouping over adversarial key patterns, dedup
correctness, session splitting, and the sum table's exactness."""

import numpy as np
import pytest

import flink_tpu.native as nat

pytestmark = pytest.mark.skipif(not nat.available(),
                                reason="native runtime unavailable")


_EXTREMES = np.array([0, 1, 2 ** 63 - 1, 2 ** 64 - 1,
                      0x9E3779B97F4A7C15], np.uint64)

KEY_PATTERNS = [
    ("uniform_small", lambda rng, n: rng.integers(0, 50, n)),
    ("uniform_wide", lambda rng, n: rng.integers(0, 2 ** 63, n)),
    ("all_equal", lambda rng, n: np.full(n, 7)),
    # index-select keeps the exact uint64 bit patterns (choice over a
    # python list would round-trip through float64 and corrupt them)
    ("extremes", lambda rng, n: _EXTREMES[rng.integers(0, 5, n)]),
    ("high_bits_only", lambda rng, n: rng.integers(0, 4, n).astype(
        np.uint64) << np.uint64(60)),
]
_SEED = {name: i * 1000 + 17 for i, (name, _) in enumerate(KEY_PATTERNS)}


@pytest.mark.parametrize("name,gen", KEY_PATTERNS)
def test_sum_log_fire_matches_python(name, gen):
    rng = np.random.default_rng(_SEED[name])
    n = 5000
    keys = gen(rng, n).astype(np.uint64)
    vals = rng.random(n)
    ok, osum = nat.sum_log_fire(keys, vals)
    want = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        want[k] = want.get(k, 0.0) + v
    got = dict(zip(ok.tolist(), osum.tolist()))
    assert set(got) == set(want)
    for k in want:
        assert got[k] == pytest.approx(want[k], rel=1e-9)
    # key-sorted output
    assert np.all(np.diff(ok.astype(np.uint64)) > 0) or len(ok) <= 1


@pytest.mark.parametrize("name,gen", KEY_PATTERNS)
def test_hll_compact_matches_python(name, gen):
    rng = np.random.default_rng(_SEED[name] + 1)
    n = 4000
    keys = gen(rng, n).astype(np.uint64)
    regs = rng.integers(0, 1024, n).astype(np.uint16)
    ranks = rng.integers(1, 40, n).astype(np.uint8)
    ck, cr, crk, ends = nat.hll_log_compact(keys, regs, ranks, 10)
    want = {}
    for k, r, rk in zip(keys.tolist(), regs.tolist(), ranks.tolist()):
        cur = want.setdefault(k, {})
        cur[r] = max(cur.get(r, 0), rk)
    got = {}
    for k, r, rk in zip(ck.tolist(), cr.tolist(), crk.tolist()):
        got.setdefault(k, {})[r] = rk
    assert got == want
    # ends partition the cells by key
    assert ends[-1] == len(ck)
    assert np.all(np.diff(ends) > 0)


def test_empty_inputs():
    e64 = np.empty(0, np.uint64)
    ok, osum = nat.sum_log_fire(e64, np.empty(0))
    assert len(ok) == 0
    ck, cr, crk, ends = nat.hll_log_compact(
        e64, np.empty(0, np.uint16), np.empty(0, np.uint8), 10)
    assert len(ck) == 0 and len(ends) == 0


def test_session_fire_negative_timestamps():
    """Signed timestamps order correctly under the radix (sign-bit
    bias): a session spanning negative->positive time stays one run."""
    keys = np.array([5, 5, 5], np.uint64)
    ts = np.array([-1500, -800, -100], np.int64)
    ok, os_, oe, ot, retained = nat.session_log_fire(
        keys, ts, np.ones(3, np.float32),
        np.array([1, 2, 3], np.uint64), 1000, 10_000, 2, 32)
    assert len(ok) == 1
    assert (int(os_[0]), int(oe[0]), float(ot[0])) == (-1500, 900, 3.0)
    assert len(retained[0]) == 0


def test_session_fire_retains_open_sessions():
    keys = np.array([1, 1, 2], np.uint64)
    ts = np.array([0, 100, 5000], np.int64)
    ok, os_, oe, ot, retained = nat.session_log_fire(
        keys, ts, np.ones(3, np.float32),
        np.array([9, 9, 9], np.uint64), 500, 4000, 2, 32)
    # key 1's session [0, 600) closed; key 2's [5000, 5500) still open
    assert [int(k) for k in ok] == [1]
    rk, rt, rw, rv = retained
    assert rk.tolist() == [2] and rt.tolist() == [5000]


def test_qsketch_fire_quantile_positions():
    # one key, bucket counts chosen so q50/q99 land in known buckets
    keys = np.zeros(100, np.uint64)
    buckets = np.concatenate([np.full(50, 3), np.full(49, 7),
                              np.full(1, 9)]).astype(np.uint16)
    import math
    log_gamma = math.log(1.1)
    ok, q = nat.qsketch_log_fire(keys, buckets, 16, [0.5, 0.99],
                                 log_gamma, 0, 1.0)
    assert len(ok) == 1
    b50 = math.exp((3 - 0.5) * log_gamma)
    b99 = math.exp((7 - 0.5) * log_gamma)
    assert q[0, 0] == pytest.approx(b50, rel=1e-9)
    assert q[0, 1] == pytest.approx(b99, rel=1e-9)


def test_sumtab_growth_from_small():
    """The dense table starts tiny and grows; sums survive rehashes."""
    t = nat.NativeSumTable(16)
    rng = np.random.default_rng(31)
    keys = rng.integers(0, 3000, 30_000).astype(np.uint64)
    vals = rng.random(30_000)
    consumed = t.ingest(keys, vals, 1 << 19)
    assert consumed == len(keys)
    ek, es = t.export()
    want = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        want[k] = want.get(k, 0.0) + v
    got = dict(zip(ek.tolist(), es.tolist()))
    assert set(got) == set(want)
    for k in want:
        assert got[k] == pytest.approx(want[k], rel=1e-9)


# ---- string interner -------------------------------------------------------

def test_interner_dense_first_seen_ids():
    it = nat.NativeStringInterner()
    a = np.asarray(["b", "a", "b", "c", "a"])
    ids, first = it.intern(a)
    assert ids.tolist() == [0, 1, 0, 2, 1]
    assert a[first].tolist() == ["b", "a", "c"]
    assert it.n == 3


def test_interner_width_independent():
    """The same word must intern to the same id whatever fixed width
    its batch happened to have."""
    it = nat.NativeStringInterner()
    ids1, _ = it.intern(np.asarray(["cat", "x"]))          # <U3
    ids2, _ = it.intern(np.asarray(["cat", "elephantine"]))  # <U11
    assert ids1[0] == ids2[0]
    assert it.n == 3


def test_interner_collision_exactness():
    """Grouping is content-exact: a large vocabulary interns with no
    id collisions and round-trips through the directory."""
    rng = np.random.default_rng(3)
    vocab = np.asarray([f"w{i}suffix{i % 97}" for i in range(20_000)])
    order = rng.permutation(40_000) % 20_000
    batch = vocab[order]
    it = nat.NativeStringInterner()
    ids, first = it.intern(batch)
    assert it.n == 20_000
    directory = batch[first]
    # every occurrence maps back to its own word
    assert (directory[ids.astype(np.int64)] == batch).all()


def test_interner_unicode_and_bytes():
    it = nat.NativeStringInterner()
    ids, _ = it.intern(np.asarray(["héllo", "日本語", "héllo"]))
    assert ids.tolist() == [0, 1, 0]
    itb = nat.NativeStringInterner()
    idsb, _ = itb.intern(np.asarray([b"ab", b"cd", b"ab"]))
    assert idsb.tolist() == [0, 1, 0]


def test_interner_empty_strings_and_restore_order():
    it = nat.NativeStringInterner()
    a = np.asarray(["", "x", ""])
    ids, first = it.intern(a)
    assert ids.tolist() == [0, 1, 0]
    # restore contract: re-interning the directory in order on a fresh
    # interner reproduces the ids
    directory = a[first]
    it2 = nat.NativeStringInterner()
    ids2, _ = it2.intern(directory)
    assert ids2.tolist() == list(range(len(directory)))


def test_string_baseline_runs():
    words = np.asarray([f"w{i % 100}" for i in range(5000)])
    rate = nat.heap_tumbling_baseline_str(words, np.ones(5000))
    assert rate > 0


def test_ivjoin_many_small_batches_with_pruning():
    """Streaming-lifetime shape for the LSM join core: thousands of
    tiny pushes with the watermark keeping pace — results must match
    one big push, and tails must keep folding (bounded run count is
    what the IV_MAX_TAILS merge trigger guarantees)."""
    import numpy as np
    import flink_tpu.native as nat
    if not nat.available():
        import pytest
        pytest.skip("native runtime required")
    rng = np.random.default_rng(5)
    n = 40_000
    lk = nat.splitmix64(rng.integers(0, 300, n).astype(np.uint64))
    lts = np.sort(rng.integers(0, 200_000, n).astype(np.int64))
    rk = nat.splitmix64(rng.integers(0, 300, n).astype(np.uint64))
    rts = np.sort(rng.integers(0, 200_000, n).astype(np.int64))

    # reference: one push per side, no pruning
    big = nat.NativeIntervalJoin(-50, 50)
    bl, br = big.push(0, lk, lts)
    bl2, br2 = big.push(1, rk, rts)
    want = set(zip(bl.tolist(), br.tolist())) \
        | set(zip(bl2.tolist(), br2.tolist()))

    # 800 interleaved pushes of 100 rows with a trailing watermark
    # (prunes rows already matched — emitted pairs are unaffected)
    small = nat.NativeIntervalJoin(-50, 50)
    got = set()
    step = 100
    for off in range(0, n, step):
        for side, (k, t) in ((0, (lk, lts)), (1, (rk, rts))):
            l, r = small.push(side, k[off:off + step],
                              t[off:off + step])
            got.update(zip(l.tolist(), r.tolist()))
        wm = int(min(lts[min(off + step, n) - 1],
                     rts[min(off + step, n) - 1])) - 200
        small.prune(wm)
    assert got == want and len(want) > 2_000


def test_session_fire_two_segment_retained_merge():
    """The retained tuple from one fire feeds the next verbatim
    (key-major contract): chained two-segment fires must produce
    exactly the sessions of one big fire."""
    import numpy as np
    import flink_tpu.native as nat
    if not nat.available():
        import pytest
        pytest.skip("native runtime required")
    rng = np.random.default_rng(17)
    n = 30_000
    keys = rng.integers(0, 500, n).astype(np.uint64)
    ts = np.sort(rng.integers(0, 100_000, n)).astype(np.int64)
    w = np.ones(n, np.float32)
    vh = nat.splitmix64(rng.integers(0, 1 << 30, n).astype(np.uint64))

    # oracle: single fire over everything
    ok, os_, oe, ot, _ = nat.session_log_fire(keys, ts, w, vh,
                                              800, 10**9, 4, 128)
    want = {(int(k), int(s), int(e)): t
            for k, s, e, t in zip(ok, os_, oe, ot)}

    # chained: 6 chunked fires, retained tuple passed back verbatim
    got = {}
    ret = None
    chunk = n // 6 + 1
    for off in range(0, n, chunk):
        hi = min(off + chunk, n)
        wm = int(ts[hi - 1]) - 1500 if hi < n else 10**9
        ok, os_, oe, ot, ret = nat.session_log_fire(
            keys[off:hi], ts[off:hi], w[off:hi], vh[off:hi],
            800, wm, 4, 128, retained=ret)
        for k, s, e, t in zip(ok, os_, oe, ot):
            got[(int(k), int(s), int(e))] = t
        if len(ret[0]) == 0:
            ret = None
    assert got == want and len(want) > 1000


def test_session_fire_guard_demotes_predating_rows():
    """A new row that predates a retained row (out-of-order across the
    fire boundary) must demote the kernel to the pooled double-sort —
    sessions still merge correctly."""
    import numpy as np
    import flink_tpu.native as nat
    if not nat.available():
        import pytest
        pytest.skip("native runtime required")
    k = np.array([7, 7], np.uint64)
    w = np.ones(2, np.float32)
    vh = nat.splitmix64(np.array([1, 2], np.uint64))
    # fire 1: both rows open (watermark behind), retained comes back
    _, _, _, _, ret = nat.session_log_fire(
        k, np.array([1000, 1400], np.int64), w, vh, 500, 0, 2, 64)
    assert len(ret[0]) == 2
    # fire 2: a new row at ts=700 PREDATES retained max (1400) and
    # bridges nothing; plus a row at 1650 extending the session
    k2 = np.array([7, 7], np.uint64)
    ok, os_, oe, ot, ret2 = nat.session_log_fire(
        k2, np.array([700, 1650], np.int64), w, vh[:2], 500, 10**9,
        2, 64, retained=ret)
    got = {(int(s), int(e)): int(t) for s, e, t in zip(os_, oe, ot)}
    # 700 joins [1000,1400,1650] because 1000-700 <= 500: one session
    # [700, 2150) of 4 events
    assert got == {(700, 2150): 4}, got
